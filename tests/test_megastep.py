"""Device-side multi-step decode — the MEGASTEP (ISSUE 7) and its
UNIVERSAL extension (ISSUE 12).

The tentpole contract: with ``megastep_k = k`` the engine fuses k decode
iterations into ONE device dispatch — an on-device scan over the ragged
program with device-resident sampling, per-lane on-device stop flags
(EOS / stop ids / max-tokens; lanes that stop early run masked no-op
iterations), and the host draining outputs every k steps through the
double-buffered fetch — and the token stream stays BIT-IDENTICAL to
k=1: greedy AND seeded temperature (+ top-k/top-p + logprobs), waves AND
chunked scheduling, async execution on AND off. Stops only the host can
see (stop ids truncated off the device watch, stop strings, cancels)
roll back via the ``num_computed_tokens`` cursor; block headroom for all
k tokens per lane is reserved at plan time, so mid-megastep block
exhaustion is impossible by construction (pressure surfaces as
drain→preempt BEFORE the dispatch).

ISSUE 12 lifts the first cut's k=1 carve-outs: chunked mixed steps and
spec verify rows now ride the same scanned body — verify rows resolve
accept/reject ON DEVICE (rejected drafts roll back inside the dispatch
via the lane's position cursor) and prefill chunks that complete their
prompt continue as decode rows in the remaining inner iterations. The
only forced-k=1 path left is a stop watch wider than the device's
MEGASTEP_WATCH_W slots, surfaced on the megastep_forced_single gauge.
"""

import asyncio

import jax.numpy as jnp
import numpy as np
import pytest

from dynamo_tpu import tracing
from dynamo_tpu.engine import EngineCore, tiny_engine, tiny_model
from dynamo_tpu.engine.core import MEGASTEP_WATCH_W
from dynamo_tpu.engine.sampler import stop_flags
from dynamo_tpu.llm.protocols.common import (
    PreprocessedRequest,
    SamplingOptions,
    StopConditions,
)

pytestmark = [pytest.mark.unit]

CFG = tiny_model()


def _req(prompt, rid, max_tokens=8, temperature=0.0, seed=None, top_k=0,
         top_p=1.0, logprobs=None, **stop_kw):
    pre = PreprocessedRequest(
        model="tiny",
        token_ids=prompt,
        request_id=rid,
        sampling=SamplingOptions(
            temperature=temperature, seed=seed, top_k=top_k, top_p=top_p
        ),
        stop=StopConditions(max_tokens=max_tokens, **stop_kw),
    )
    if logprobs is not None:
        pre.output.logprobs = logprobs
    return pre


def drive(core, seqs, max_steps=4000):
    done = {s.request_id: [] for s in seqs}
    fins: dict[str, str] = {}
    lps = {s.request_id: [] for s in seqs}
    for _ in range(max_steps):
        for s, out in core.step():
            done[s.request_id].extend(out.token_ids)
            if out.logprobs:
                lps[s.request_id].extend(out.logprobs)
            if out.finish_reason:
                fins[s.request_id] = out.finish_reason
        if len(fins) == len(seqs) and not core.has_work():
            break
    return done, fins, lps


def _workload(core):
    """Greedy + seeded-temperature + top-k + top-p + logprobs lanes with
    staggered budgets, plus one long prompt (exercises prefill waves /
    chunks between megasteps)."""
    rng = np.random.RandomState(0)
    seqs = [
        core.add_request(_req(
            list(range(i + 1, i + 9)), f"g{i}", max_tokens=10 + i,
            ignore_eos=True,
        ))
        for i in range(3)
    ]
    seqs.append(core.add_request(_req(
        [3, 5, 7, 9], "t", max_tokens=13, temperature=0.8, seed=11,
        ignore_eos=True,
    )))
    seqs.append(core.add_request(_req(
        [4, 6, 8], "k", max_tokens=9, temperature=0.7, seed=12, top_k=8,
        ignore_eos=True,
    )))
    seqs.append(core.add_request(_req(
        [2, 4, 6, 8, 10], "p", max_tokens=11, temperature=0.9, seed=13,
        top_p=0.8, logprobs=3, ignore_eos=True,
    )))
    seqs.append(core.add_request(_req(
        list(rng.randint(1, 200, size=120)), "long", max_tokens=6,
        ignore_eos=True,
    )))
    return seqs


# -- config resolution --------------------------------------------------------


def test_megastep_resolution_and_validation():
    # 0 inherits the legacy decode_chain knob; >= 1 overrides it.
    assert tiny_engine(decode_chain=8).megastep == 8
    assert tiny_engine(decode_chain=8, megastep_k=1).megastep == 1
    assert tiny_engine(decode_chain=1, megastep_k=16).megastep == 16
    with pytest.raises(ValueError, match="megastep_k"):
        EngineCore(CFG, tiny_engine(megastep_k=-1), seed=0)


# -- bit-identical parity -----------------------------------------------------


@pytest.mark.parametrize("scheduling", ["waves", "chunked"])
@pytest.mark.parametrize(
    "k", [pytest.param(2, marks=pytest.mark.slow), 8]
)  # k=2 rides the slow tier; k=8 keeps both scheduling modes in tier-1
def test_parity_megastep_vs_single_step(scheduling, k):
    """The acceptance invariant: --megastep-k k vs 1, same tokens, same
    finish reasons, same logprob payloads — greedy and seeded lanes in
    one batch, under both schedulers."""

    def run(kk):
        core = EngineCore(
            CFG,
            tiny_engine(
                megastep_k=kk, scheduling=scheduling, prefill_chunk=32
            ),
            seed=0,
        )
        return drive(core, _workload(core))

    assert run(1) == run(k)


@pytest.mark.parametrize(
    "async_exec", [pytest.param(False, marks=pytest.mark.slow), True]
)  # async OFF re-runs the plain matrix above; tier-1 keeps the ON cell
def test_parity_megastep_async_composition(async_exec):
    """Megastep x async-exec compose: one k-iteration dispatch in flight
    while the next is planned against the optimistic overlay; stream
    identical to the synchronous single-step loop."""

    def run(kk, ae):
        core = EngineCore(
            CFG, tiny_engine(megastep_k=kk, async_exec=ae), seed=0
        )
        return drive(core, _workload(core))

    assert run(1, False) == run(8, async_exec)


def test_async_megastep_dispatch_precedes_landing():
    """The pipelining contract survives k > 1: in steady decode, the
    NEXT megastep is dispatched before the previous one's outputs land."""
    core = EngineCore(CFG, tiny_engine(megastep_k=8, async_exec=True), seed=0)
    core._exec_log = []
    seq = core.add_request(_req([1, 2, 3], "s", max_tokens=40, ignore_eos=True))
    drive(core, [seq])
    events = core._exec_log
    overlapped = any(
        ("dispatch", n + 1) in events
        and events.index(("dispatch", n + 1)) < events.index(("land", n))
        for kind, n in events
        if kind == "dispatch" and ("land", n) in events
    )
    assert overlapped, events
    assert core.exec_stats["megastep_dispatches"] >= 2


# -- on-device stop flags -----------------------------------------------------


def test_stop_flags_device_logic():
    """The pure stop-flag predicate: watch hits gate on the min-tokens
    floor, budgets fire exactly at the remaining-token edge, and the -1
    padding can never match a real token id."""
    watch = jnp.asarray([[5, -1], [7, 9], [-1, -1], [2, -1]], jnp.int32)
    budgets = jnp.asarray([10, 10, 3, 10], jnp.int32)
    min_left = jnp.asarray([0, 4, 0, 0], jnp.int32)
    sampled = jnp.asarray([5, 9, 0, 3], jnp.int32)
    # i=0 -> gen=1: lane0 watch-hits; lane1 watch-hits but sits under its
    # min-tokens floor (gen 1 < 4); lane2 budget 3 not yet; lane3 clean.
    f0 = np.asarray(stop_flags(sampled, watch, budgets, min_left, jnp.int32(0)))
    assert f0.tolist() == [True, False, False, False]
    # i=3 -> gen=4: lane1's floor passes; lane2 exhausted its budget at
    # gen=3 already (flag recomputed per-iteration — still True at 4).
    f3 = np.asarray(stop_flags(sampled, watch, budgets, min_left, jnp.int32(3)))
    assert f3.tolist() == [True, True, True, False]
    # -1 padding never fires even if a lane "samples" garbage id 0.
    pad_only = jnp.full((4, 2), -1, jnp.int32)
    f = np.asarray(stop_flags(
        jnp.zeros(4, jnp.int32), pad_only,
        jnp.full(4, 99, jnp.int32), jnp.zeros(4, jnp.int32), jnp.int32(0),
    ))
    assert not f.any()


def test_eos_inside_megastep():
    """A lane that samples EOS at an inner iteration of a k=8 megastep
    finishes with reason 'eos' and emits exactly the same stream as the
    single-step engine; its surviving batch neighbors are untouched.
    Seeded temperature (the tiny model's greedy stream is a fixed point,
    so a fresh mid-stream EOS only exists on a sampled lane — which also
    pins the on-device stop flag against the seeded replay path)."""
    probe = EngineCore(CFG, tiny_engine(megastep_k=1), seed=0)
    s = probe.add_request(_req(
        [1, 2, 3], "p", max_tokens=12, temperature=0.9, seed=42,
        ignore_eos=True,
    ))
    d, _, _ = drive(probe, [s])
    eos = d["p"][4]  # mid-stream token -> EOS lands INSIDE a k=8 megastep
    if eos in d["p"][:4]:
        pytest.skip("seeded stream repeats before position 4")

    def run(k):
        core = EngineCore(
            CFG, tiny_engine(megastep_k=k), seed=0, eos_token_ids=(eos,)
        )
        seqs = [
            core.add_request(_req(
                [1, 2, 3], "e", max_tokens=12, temperature=0.9, seed=42,
            )),
            core.add_request(_req([9, 9, 9], "n", max_tokens=12,
                                  ignore_eos=True)),
        ]
        return drive(core, seqs)[:2]

    d1, f1 = run(1)
    d8, f8 = run(8)
    assert d1 == d8
    assert f1 == f8
    assert f8["e"] == "eos"
    assert d8["e"] == d["p"][:5]  # stopped mid-megastep, not at a boundary


def test_host_only_stop_rolls_back_at_megastep_boundary():
    """A stop id truncated OFF the device watch (the lane carries more
    stop ids than MEGASTEP_WATCH_W) is invisible to the on-device flags:
    the megastep runs past it, and the host stop-scan rolls the cursor
    back — the late-stop/stop-string rollback story. Stream and finish
    reason still match k=1 exactly."""
    probe = EngineCore(CFG, tiny_engine(megastep_k=1), seed=0)
    s = probe.add_request(_req([9, 9, 9], "p", max_tokens=20, ignore_eos=True))
    d, _, _ = drive(probe, [s])
    stop_tok = d["p"][5]
    # Decoys (never sampled by this greedy stream) fill the device watch;
    # the REAL stop id is last and falls off the [B, W] array.
    decoys = [t for t in range(300, 300 + MEGASTEP_WATCH_W)]
    stop_ids = decoys + [stop_tok]

    def run(k, async_exec=False):
        core = EngineCore(
            CFG, tiny_engine(megastep_k=k, async_exec=async_exec), seed=0
        )
        seq = core.add_request(_req(
            [9, 9, 9], "x", max_tokens=20, stop_token_ids=stop_ids,
            ignore_eos=True,
        ))
        out = drive(core, [seq])[:2]
        assert core.allocator._partials == 0
        return out

    d1, f1 = run(1)
    d8, f8 = run(8)
    assert d1 == d8 == {"x": d["p"][:6]}
    assert f1 == f8 == {"x": "stop"}
    # And one megastep later under async: the stop lands a whole
    # in-flight megastep late and the zombie lane's k tokens discard.
    assert run(8, async_exec=True) == (d1, f1)


def test_watch_overflow_forces_single_step():
    """ISSUE 8 satellite: a request watching MORE stop ids than the
    device's MEGASTEP_WATCH_W slots must not silently truncate the
    watch — its megasteps run at k=1, where the host stop-scan (which
    checks the FULL list) sees every token before the next dispatch.
    9 stop ids inside a configured k=8 megastep: correct stream, correct
    finish, and ZERO fused dispatches."""
    probe = EngineCore(CFG, tiny_engine(megastep_k=1), seed=0)
    s = probe.add_request(_req([9, 9, 9], "p", max_tokens=20, ignore_eos=True))
    d, _, _ = drive(probe, [s])
    stop_tok = d["p"][5]
    # W decoys + the real stop id = W+1 watch entries: one over the slots.
    stop_ids = list(range(300, 300 + MEGASTEP_WATCH_W)) + [stop_tok]
    assert len(stop_ids) == MEGASTEP_WATCH_W + 1

    core = EngineCore(CFG, tiny_engine(megastep_k=8), seed=0)
    seq = core.add_request(_req(
        [9, 9, 9], "x", max_tokens=20, stop_token_ids=stop_ids,
        ignore_eos=True,
    ))
    done, fins, _ = drive(core, [seq])
    assert done == {"x": d["p"][:6]}
    assert fins == {"x": "stop"}
    # The overflow forced every decode dispatch to k=1 — no fused
    # megasteps ran, so the truncated device watch never decided anything.
    assert core.exec_stats["megastep_dispatches"] == 0
    assert core.exec_stats["single_step_dispatches"] > 0

    # Control: the same stream with a watch that FITS stays fused.
    core8 = EngineCore(CFG, tiny_engine(megastep_k=8), seed=0)
    seq8 = core8.add_request(_req(
        [9, 9, 9], "y", max_tokens=20,
        stop_token_ids=stop_ids[1:],  # exactly W ids, real stop included
        ignore_eos=True,
    ))
    done8, fins8, _ = drive(core8, [seq8])
    assert done8 == {"y": d["p"][:6]} and fins8 == {"y": "stop"}
    assert core8.exec_stats["megastep_dispatches"] >= 1


def test_cancel_mid_megastep_discards_in_flight_tokens():
    """Host-side aborts (client disconnect, detokenizer stop-string
    match) cancel between steps: the in-flight megastep's tokens for
    that lane are discarded at commit and its blocks release exactly
    once."""
    core = EngineCore(CFG, tiny_engine(megastep_k=8, async_exec=True), seed=0)
    seq = core.add_request(_req([1, 2, 3], "c", max_tokens=50, ignore_eos=True))
    core.step()  # dispatch prefill
    core.step()  # dispatch megastep 1, commit prefill
    core.cancel_request(seq)
    for _ in range(5):
        core.step()
    assert not core.has_work()
    assert seq not in core.running
    assert core.allocator._partials == 0


# -- block headroom (reserved at plan time) -----------------------------------


@pytest.mark.parametrize("async_exec", [False, True])
def test_block_headroom_under_pressure(async_exec):
    """k tokens of per-lane block headroom are grown BEFORE the dispatch
    is enqueued, so pressure surfaces as preemption (sync) or
    drain-then-preempt (async) at plan time — never as mid-megastep
    exhaustion — and the replayed stream still matches an unpressured
    single-step run."""

    def run(blocks, k, ae):
        core = EngineCore(
            CFG,
            tiny_engine(
                num_kv_blocks=blocks, max_model_len=64, megastep_k=k,
                async_exec=ae,
            ),
            seed=0,
        )
        seqs = [
            core.add_request(_req(list(range(1, 17)), "a", max_tokens=24,
                                  ignore_eos=True)),
            core.add_request(_req(list(range(20, 36)), "b", max_tokens=24,
                                  ignore_eos=True)),
        ]
        done, fins, _ = drive(core, seqs, max_steps=8000)
        assert core.allocator._partials == 0
        return done, fins, core

    ref = run(64, 1, False)[:2]  # plentiful blocks, single-step
    d, f, core = run(7, 8, async_exec)
    assert (d, f) == ref
    assert core.sched_stats["preemptions"] >= 1
    if async_exec:
        assert core.exec_stats["drains"] >= 1


# -- observability ------------------------------------------------------------


def test_megastep_span_and_dispatch_gauges():
    tracing.configure(enabled=True, sample=1.0)
    collector = tracing.get_collector()
    collector.clear()
    core = EngineCore(CFG, tiny_engine(megastep_k=8), seed=0)
    seq = core.add_request(_req([1, 2, 3], "m", max_tokens=20, ignore_eos=True))
    drive(core, [seq])
    spans = [s for s in collector.stats() if s.name == "engine_megastep"]
    assert spans, "engine_megastep span missing"
    assert all(s.attrs["inner_steps"] > 1 for s in spans)
    assert sum(s.attrs["tokens"] for s in spans) <= 20
    st = core.scheduler_stats()
    assert st["megastep_k"] == 8
    assert st["megastep_dispatches"] == len(spans)
    assert st["single_step_dispatches"] >= 1  # the prefill wave
    assert st["committed_tokens"] == 20
    # The amortization gauge: fewer dispatches than tokens.
    assert 0 < st["dispatches_per_token"] < 1.0


def test_single_step_engine_reports_no_megasteps():
    tracing.configure(enabled=True, sample=1.0)
    collector = tracing.get_collector()
    collector.clear()
    core = EngineCore(CFG, tiny_engine(megastep_k=1), seed=0)
    seq = core.add_request(_req([1, 2, 3], "s", max_tokens=8, ignore_eos=True))
    drive(core, [seq])
    assert not [s for s in collector.stats() if s.name == "engine_megastep"]
    st = core.scheduler_stats()
    assert st["megastep_dispatches"] == 0
    assert st["dispatches_per_token"] >= 1.0  # one dispatch per token + prefill


def test_spec_verify_rows_fuse_on_device():
    """ISSUE 12: speculating lanes RIDE the megastep — verify rows
    resolve accept/reject inside the scanned dispatch (rejected drafts
    roll back on device) and the stream still matches the unfused,
    unspeculated engine bit for bit."""

    def run(**kw):
        core = EngineCore(CFG, tiny_engine(**kw), seed=0)
        repeat = [3, 4, 5, 3, 4, 5, 3, 4]  # n-gram bait
        seq = core.add_request(_req(repeat, "sp", max_tokens=16,
                                    ignore_eos=True))
        out = drive(core, [seq])[:2]
        return out, core

    ref, _ = run(megastep_k=1)
    got, core = run(megastep_k=8, spec_decode="ngram", spec_k=4)
    assert got == ref
    assert core.exec_stats["fused_mixed_dispatches"] >= 1
    assert core.exec_stats["megastep_dispatches"] >= 1
    assert core.exec_stats["megastep_forced_single"] == 0
    assert core.spec_stats.verify_rows > 0


# -- universal megastep (ISSUE 12): fused mixed + spec-verify steps ----------


def _spec_workload(core):
    """Speculation-heavy mixed traffic: repetitive prompts (n-gram bait)
    across greedy, seeded-temperature, and top-p + logprobs lanes, one
    incompressible decode lane (drafts rarely), and one long prompt so
    chunked scheduling interleaves prefill chunks with fused verify
    rows."""
    rng = np.random.RandomState(7)
    return [
        core.add_request(_req([3, 4, 5] * 4, "sg", max_tokens=18,
                              ignore_eos=True)),
        core.add_request(_req([7, 8] * 6, "st", max_tokens=15,
                              temperature=0.8, seed=21, ignore_eos=True)),
        core.add_request(_req([2, 4, 6, 2, 4, 6, 2, 4], "sl", max_tokens=12,
                              temperature=0.9, seed=22, top_p=0.85,
                              logprobs=3, ignore_eos=True)),
        core.add_request(_req(list(range(1, 9)), "pd", max_tokens=14,
                              ignore_eos=True)),
        core.add_request(_req(list(rng.randint(1, 200, size=120)), "long",
                              max_tokens=6, ignore_eos=True)),
    ]


@pytest.mark.parametrize("scheduling", ["waves", "chunked"])
@pytest.mark.parametrize(
    "k", [pytest.param(2, marks=pytest.mark.slow), 8]
)  # k=2 rides the slow tier; k=8 keeps both scheduling modes in tier-1
def test_parity_fused_mixed_spec(scheduling, k):
    """The ISSUE 12 acceptance invariant: with spec decode ON and mixed
    traffic, --megastep-k k fuses verify rows (accept/reject resolved on
    device) and prefill chunks into scanned dispatches, and the stream —
    tokens, finish reasons, logprob payloads — is bit-identical to the
    single-step engine AND to the unspeculated single-step engine."""

    def run(kk, spec):
        core = EngineCore(
            CFG,
            tiny_engine(
                megastep_k=kk, scheduling=scheduling, prefill_chunk=32,
                **(dict(spec_decode="ngram", spec_k=4) if spec else {}),
            ),
            seed=0,
        )
        return drive(core, _spec_workload(core)), core

    base, _ = run(1, spec=False)
    ref, _ = run(1, spec=True)
    got, core = run(k, spec=True)
    assert base == ref == got
    assert core.exec_stats["fused_mixed_dispatches"] >= 1
    assert core.exec_stats["megastep_forced_single"] == 0
    assert core.spec_stats.verify_rows > 0


@pytest.mark.parametrize(
    "async_exec", [pytest.param(False, marks=pytest.mark.slow), True]
)  # async OFF re-runs the plain matrix above; tier-1 keeps the ON cell
def test_parity_fused_async_composition(async_exec):
    """Universal megastep x async-exec: fused steps carrying live drafts
    are a pipeline barrier (data-dependent advance), draft-less fused
    steps keep the one-step-ahead overlap — stream identical to the
    synchronous single-step loop either way."""

    def run(kk, ae, spec):
        core = EngineCore(
            CFG,
            tiny_engine(
                megastep_k=kk, scheduling="chunked", prefill_chunk=32,
                async_exec=ae,
                **(dict(spec_decode="ngram", spec_k=4) if spec else {}),
            ),
            seed=0,
        )
        return drive(core, _spec_workload(core))

    assert run(1, False, spec=False) == run(8, async_exec, spec=True)


def test_eos_inside_fused_verify_continuation():
    """A seeded lane that samples EOS inside the scanned continuation of
    a FUSED verify dispatch finishes identically to the single-step
    engine — the on-device stop flags see it (masked no-ops follow), the
    host stop-scan confirms it, and the spec machinery never resurrects
    the lane."""
    probe = EngineCore(CFG, tiny_engine(megastep_k=1), seed=0)
    s = probe.add_request(_req(
        [5, 6] * 4, "p", max_tokens=12, temperature=0.9, seed=42,
        ignore_eos=True,
    ))
    d, _, _ = drive(probe, [s])
    eos = d["p"][4]
    if eos in d["p"][:4]:
        pytest.skip("seeded stream repeats before position 4")

    def run(k):
        core = EngineCore(
            CFG,
            tiny_engine(megastep_k=k, spec_decode="ngram", spec_k=4),
            seed=0, eos_token_ids=(eos,),
        )
        seqs = [
            core.add_request(_req(
                [5, 6] * 4, "e", max_tokens=12, temperature=0.9, seed=42,
            )),
            core.add_request(_req([3, 4, 5] * 3, "n", max_tokens=12,
                                  ignore_eos=True)),
        ]
        return drive(core, seqs)[:2]

    d1, f1 = run(1)
    d8, f8 = run(8)
    assert d1 == d8
    assert f1 == f8
    assert f8["e"] == "eos"


def test_fused_gauges_and_span_shapes():
    """Observability (ISSUE 12 satellite): fused mixed dispatches export
    on the scheduler gauges, and every engine_megastep span carries a
    fused_shapes attr with decode/chunk/verify row counts."""
    tracing.configure(enabled=True, sample=1.0)
    collector = tracing.get_collector()
    collector.clear()
    core = EngineCore(
        CFG,
        tiny_engine(
            megastep_k=8, scheduling="chunked", prefill_chunk=32,
            spec_decode="ngram", spec_k=4,
        ),
        seed=0,
    )
    drive(core, _spec_workload(core))
    spans = [s for s in collector.stats() if s.name == "engine_megastep"]
    assert spans, "engine_megastep span missing"
    assert all("fused_shapes" in s.attrs for s in spans)
    assert all(s.attrs["inner_steps"] > 1 for s in spans)
    assert any(s.attrs["fused_shapes"]["verify"] >= 1 for s in spans)
    assert any(s.attrs["fused_shapes"]["chunk"] >= 1 for s in spans)
    st = core.scheduler_stats()
    assert st["fused_mixed_dispatches"] >= 1
    assert st["megastep_forced_single"] == 0
    assert st["megastep_dispatches"] >= 1
    assert 0 < st["dispatches_per_token"] < 1.0


def test_watch_overflow_forces_single_step_with_spec():
    """The ONE documented forced-k=1 path survives the universal
    megastep: a speculating request watching more stop ids than the
    device's MEGASTEP_WATCH_W slots falls back to single-step verify
    dispatches (host stop-scan sees the full list), the stream stays
    correct, and the forced-single gauge records it."""
    probe = EngineCore(CFG, tiny_engine(megastep_k=1), seed=0)
    s = probe.add_request(_req([3, 4, 5] * 3, "p", max_tokens=20,
                               ignore_eos=True))
    d, _, _ = drive(probe, [s])
    stop_tok = d["p"][5]
    stop_ids = list(range(300, 300 + MEGASTEP_WATCH_W)) + [stop_tok]

    core = EngineCore(
        CFG,
        tiny_engine(megastep_k=8, spec_decode="ngram", spec_k=4),
        seed=0,
    )
    seq = core.add_request(_req(
        [3, 4, 5] * 3, "x", max_tokens=20, stop_token_ids=stop_ids,
        ignore_eos=True,
    ))
    done, fins, _ = drive(core, [seq])
    assert done == {"x": d["p"][:6]}
    assert fins == {"x": "stop"}
    assert core.exec_stats["megastep_dispatches"] == 0
    assert core.exec_stats["fused_mixed_dispatches"] == 0
    assert core.exec_stats["megastep_forced_single"] >= 1


@pytest.mark.parametrize("async_exec", [False, True])
def test_fused_block_headroom_under_pressure(async_exec):
    """The full fused headroom — n_steps per decode lane, n_steps +
    draft per verify lane, chunk + n_steps - 1 per completing prefill
    chunk — is reserved at plan time: pressure surfaces as preemption
    (or drain-then-preempt under async) BEFORE the dispatch, and the
    replayed stream still matches an unpressured single-step run."""

    def run(blocks, k, ae, spec):
        core = EngineCore(
            CFG,
            tiny_engine(
                num_kv_blocks=blocks, max_model_len=64, megastep_k=k,
                scheduling="chunked", async_exec=ae,
                **(dict(spec_decode="ngram", spec_k=4) if spec else {}),
            ),
            seed=0,
        )
        seqs = [
            core.add_request(_req([5, 6] * 8, "a", max_tokens=24,
                                  ignore_eos=True)),
            core.add_request(_req([7, 8] * 8, "b", max_tokens=24,
                                  ignore_eos=True)),
        ]
        done, fins, _ = drive(core, seqs, max_steps=8000)
        assert core.allocator._partials == 0
        return done, fins, core

    ref = run(64, 1, False, spec=False)[:2]
    d, f, core = run(7, 8, async_exec, spec=True)
    assert (d, f) == ref
    assert core.sched_stats["preemptions"] >= 1


def test_fused_waves_spec_respects_token_budget():
    """A token budget SMALLER than the speculating lane count (waves
    engine — chunked validates the budget up front, waves does not):
    over-budget lanes defer to later fused steps via the rotation cap,
    exactly like the legacy verify path's budget break — no bucket
    overflow, and the stream stays bit-identical to k=1."""

    def run(k):
        core = EngineCore(
            CFG,
            tiny_engine(
                megastep_k=k, spec_decode="ngram", spec_k=4,
                max_num_batched_tokens=4,
            ),
            seed=0,
        )
        seqs = [
            core.add_request(_req([3, 4, 5] * 3, f"s{i}", max_tokens=10,
                                  ignore_eos=True))
            for i in range(6)
        ]
        return drive(core, seqs)

    assert run(1) == run(8)


def test_cancel_mid_fused_megastep_discards_in_flight():
    """Cancel between steps with a fused mixed/verify dispatch in
    flight: the lane's optimistic tokens discard at commit and blocks
    release exactly once."""
    core = EngineCore(
        CFG,
        tiny_engine(
            megastep_k=8, scheduling="chunked", async_exec=True,
            spec_decode="ngram", spec_k=4,
        ),
        seed=0,
    )
    seq = core.add_request(_req([3, 4, 5] * 3, "c", max_tokens=50,
                                ignore_eos=True))
    core.step()  # dispatch prefill
    core.step()  # dispatch fused step 1, commit prefill
    core.cancel_request(seq)
    for _ in range(5):
        core.step()
    assert not core.has_work()
    assert seq not in core.running
    assert core.allocator._partials == 0


# -- mocker virtual-clock A/B -------------------------------------------------


def _mock_megastep_sim(k, base_iter_us=58000.0, B=16, isl=128, osl=64):
    from dynamo_tpu.llm.mocker.engine import MockEngineArgs, MockTpuEngine, _Seq
    from dynamo_tpu.tokens import TokenBlockSequence, compute_seq_hashes

    args = MockEngineArgs(
        num_kv_blocks=8192, block_size=32, max_num_seqs=B,
        max_num_batched_tokens=2048, enable_prefix_caching=False,
        base_iter_us=base_iter_us, megastep_k=k,
    )
    eng = MockTpuEngine(args)
    seqs = []
    for j in range(B):
        prompt = [1 + (j % 7)] * isl
        s = _Seq(
            request_id=f"s{j}", prompt=prompt, max_tokens=osl,
            out=asyncio.Queue(),
            seq=TokenBlockSequence(prompt, args.block_size),
            prompt_hashes=compute_seq_hashes(prompt, args.block_size),
            stop=StopConditions(max_tokens=osl, ignore_eos=True),
        )
        seqs.append(s)
        eng._waiting.append(s)
    vt = 0.0
    first: dict[str, float] = {}
    streams: dict[str, list[int]] = {s.request_id: [] for s in seqs}
    while any(s in eng._running or s in eng._waiting for s in seqs):
        eng._admit()
        p, d = eng._step()
        vt += (
            args.base_iter_us
            + p * args.prefill_us_per_token
            + d * args.decode_us_per_seq
        ) / 1e6
        for s in seqs:
            while not s.out.empty():
                item = s.out.get_nowait()
                if isinstance(item, dict) and item.get("token_ids"):
                    streams[s.request_id].extend(item["token_ids"])
                    first.setdefault(s.request_id, vt)
    decode_s = vt - max(first.values())
    tpot = decode_s / (B * (osl - 1))
    return streams, tpot, eng.scheduler_stats()


def test_mocker_megastep_ab_halves_tpot_at_k8():
    """The acceptance criterion on the mocker's deterministic virtual
    clock: with the dispatch overhead priced at the measured relay value
    (58 ms, PERF.md), fusing k=8 iterations per dispatch cuts decode
    TPOT p50 to <= 0.5x — one overhead per 8 device iterations — with a
    bit-identical stream."""
    s1, tpot1, st1 = _mock_megastep_sim(1)
    s8, tpot8, st8 = _mock_megastep_sim(8)
    assert s1 == s8
    assert tpot8 <= 0.5 * tpot1, (tpot1, tpot8)
    assert st8["megastep_dispatches"] > 0
    assert st1["megastep_dispatches"] == 0
    assert st8["dispatches_per_token"] < st1["dispatches_per_token"]
    assert st8["megastep_k"] == 8


def test_mocker_megastep_fuses_spec_lanes():
    """ISSUE 12 mocker mirror: spec verify lanes RIDE the megastep —
    fused iterations emit (1 + accepted) + (k - 1) tokens per lane under
    ONE priced dispatch, the stream stays bit-identical to k=1, and the
    fused_mixed_dispatches gauge records the lifted carve-out."""
    from dynamo_tpu.llm.mocker.engine import MockEngineArgs, MockTpuEngine

    with pytest.raises(ValueError, match="megastep_k"):
        MockTpuEngine(MockEngineArgs(megastep_k=0))
    s1, st1 = _mock_megastep_sim_spec(1)
    s8, st8 = _mock_megastep_sim_spec(8)
    assert s1 == s8
    assert st1["megastep_dispatches"] == 0
    assert st8["megastep_dispatches"] > 0
    assert st8["fused_mixed_dispatches"] > 0
    assert st8["dispatches"] < st1["dispatches"]


def _mock_megastep_sim_spec(k: int):
    from dynamo_tpu.llm.mocker.engine import MockEngineArgs, MockTpuEngine, _Seq
    from dynamo_tpu.tokens import TokenBlockSequence, compute_seq_hashes

    args = MockEngineArgs(
        num_kv_blocks=512, block_size=32, max_num_seqs=4,
        max_num_batched_tokens=2048, enable_prefix_caching=False,
        megastep_k=k, spec_decode="ngram", spec_k=4,
    )
    eng = MockTpuEngine(args)
    seqs = []
    for j in range(4):
        prompt = [1 + j] * 64
        s = _Seq(
            request_id=f"s{j}", prompt=prompt, max_tokens=32,
            out=asyncio.Queue(),
            seq=TokenBlockSequence(prompt, args.block_size),
            prompt_hashes=compute_seq_hashes(prompt, args.block_size),
            stop=StopConditions(max_tokens=32, ignore_eos=True),
        )
        s.spec_k = 4
        seqs.append(s)
        eng._waiting.append(s)
    streams: dict[str, list[int]] = {s.request_id: [] for s in seqs}
    while any(s in eng._running or s in eng._waiting for s in seqs):
        eng._admit()
        eng._step()
        for s in seqs:
            while not s.out.empty():
                item = s.out.get_nowait()
                if isinstance(item, dict) and item.get("token_ids"):
                    streams[s.request_id].extend(item["token_ids"])
    return streams, eng.scheduler_stats()
