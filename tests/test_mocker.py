"""Mock engine: KV manager lifecycle, prefix caching, scheduling, events.

Parity: reference mocker KV-manager lifecycle tests
(`lib/llm/src/mocker/kv_manager.rs:309-355`).
"""

import asyncio

import pytest

from dynamo_tpu.llm.mocker import MockEngineArgs, MockKvManager, MockTpuEngine
from dynamo_tpu.llm.mocker.kv_manager import InsufficientBlocksError
from dynamo_tpu.llm.protocols.common import PreprocessedRequest, StopConditions
from dynamo_tpu.runtime.engine import Context
from dynamo_tpu.tokens import compute_seq_hashes

pytestmark = [pytest.mark.unit, pytest.mark.pre_merge]

FAST = MockEngineArgs(
    num_kv_blocks=64,
    block_size=4,
    speedup_ratio=1000.0,
)


def make_request(tokens, max_tokens=8, request_id="r1"):
    return PreprocessedRequest(
        model="mock",
        token_ids=tokens,
        stop=StopConditions(max_tokens=max_tokens),
        request_id=request_id,
    ).to_wire()


# -- KV manager ---------------------------------------------------------------


def test_kv_manager_commit_and_release_to_lru():
    stored, removed = [], []
    kv = MockKvManager(
        num_blocks=4, block_size=4,
        on_stored=lambda h, p: stored.extend(h),
        on_removed=lambda h: removed.extend(h),
    )
    h = compute_seq_hashes([1, 2, 3, 4, 5, 6, 7, 8], 4)
    kv.allocate_partial(2)
    kv.commit_block(h[0], None)
    kv.commit_block(h[1], h[0])
    assert stored == h
    assert kv.match_prefix(h) == 2
    kv.release(h)
    # Released blocks stay cached (inactive LRU) — still matchable.
    assert kv.match_prefix(h) == 2
    assert removed == []


def test_kv_manager_eviction_under_pressure():
    removed = []
    kv = MockKvManager(num_blocks=2, block_size=4, on_removed=lambda h: removed.extend(h))
    h = compute_seq_hashes(list(range(8)), 4)
    kv.allocate_partial(2)
    kv.commit_block(h[0], None)
    kv.commit_block(h[1], h[0])
    kv.release(h)  # both inactive now
    kv.allocate_partial(2)  # requires evicting both LRU blocks
    assert removed == h
    assert kv.match_prefix(h) == 0


def test_kv_manager_insufficient_blocks():
    kv = MockKvManager(num_blocks=2, block_size=4)
    kv.allocate_partial(2)
    with pytest.raises(InsufficientBlocksError):
        kv.allocate_partial(1)


def test_kv_manager_dedup_on_commit():
    stored = []
    kv = MockKvManager(num_blocks=8, block_size=4, on_stored=lambda h, p: stored.extend(h))
    h = compute_seq_hashes([1, 2, 3, 4], 4)
    kv.allocate_partial(1)
    kv.commit_block(h[0], None)
    kv.allocate_partial(1)
    kv.commit_block(h[0], None)  # second seq, same content → dedup, no event
    assert stored == [h[0]]
    assert kv.used_blocks == 1


# -- engine -------------------------------------------------------------------


def test_kv_dtype_prices_halved_bytes_and_identical_tokens():
    """ISSUE 8: with the KV-read term priced, an int8 mocker's decode
    iterations cost ~0.52x the bf16 ones on the virtual clock (the
    DMA-bound decode model), while token VALUES are bit-identical; the
    default kv_read_us_per_block=0 keeps legacy timing untouched."""
    from dynamo_tpu.engine.kv_quant import kv_byte_ratio
    from dynamo_tpu.tokens import TokenBlockSequence
    from dynamo_tpu.llm.mocker.engine import _Seq

    def run(kv_dtype, kv_us):
        args = MockEngineArgs(
            num_kv_blocks=256, block_size=4, max_num_seqs=4,
            enable_prefix_caching=False, kv_dtype=kv_dtype,
            kv_read_us_per_block=kv_us,
        )
        eng = MockTpuEngine(args)
        prompt = [1] * 16
        s = _Seq(
            request_id="s", prompt=prompt, max_tokens=8, out=asyncio.Queue(),
            seq=TokenBlockSequence(prompt, args.block_size),
            prompt_hashes=compute_seq_hashes(prompt, args.block_size),
            stop=StopConditions(max_tokens=8, ignore_eos=True),
        )
        eng._waiting.append(s)
        vt = 0.0
        toks = []
        while s in eng._waiting or s in eng._running:
            eng._admit()
            p, d = eng._step()
            vt += eng.iter_time_s(p, d, eng._last_kv_blocks_read)
            while not s.out.empty():
                item = s.out.get_nowait()
                if isinstance(item, dict):
                    toks.extend(item.get("token_ids") or [])
        return vt, toks

    t_bf, toks_bf = run("bf16", 100.0)
    t_i8, toks_i8 = run("int8", 100.0)
    assert toks_bf == toks_i8, "kv dtype changed token values"
    assert t_i8 < t_bf, "int8 KV reads were not priced cheaper"
    # The delta is exactly the byte ratio applied to the KV term.
    ratio = kv_byte_ratio("int8")
    t0, _ = run("bf16", 0.0)
    assert t_i8 - t0 == pytest.approx((t_bf - t0) * ratio, rel=1e-6)
    # And unpriced (default) int8 matches legacy timing exactly.
    assert run("int8", 0.0)[0] == pytest.approx(t0, rel=1e-9)
    # Gauges surface the dtype + halved bytes per block.
    st = MockTpuEngine(MockEngineArgs(kv_dtype="int8")).kv_cache_stats()
    st_bf = MockTpuEngine(MockEngineArgs()).kv_cache_stats()
    assert st["kv_dtype_int8"] == 1 and st_bf["kv_dtype_int8"] == 0
    assert st["bytes_per_block"] < st_bf["bytes_per_block"]


async def test_engine_generates_to_max_tokens():
    engine = MockTpuEngine(FAST)
    outs = [o async for o in engine.generate(make_request([1] * 10, max_tokens=6), Context())]
    tokens = [t for o in outs for t in o["token_ids"]]
    assert len(tokens) == 6
    assert outs[-1]["finish_reason"] == "length"
    assert outs[-1]["prompt_tokens"] == 10
    assert outs[0]["meta"]["cached_tokens"] == 0


async def test_engine_stop_token_ids_and_eos():
    # Mock decode emits 'a','b','c',... — stop on 'd' (the 4th token).
    engine = MockTpuEngine(FAST)
    req = PreprocessedRequest(
        model="mock",
        token_ids=[1] * 10,
        stop=StopConditions(max_tokens=20, stop_token_ids=[ord("d")]),
        request_id="stop1",
    ).to_wire()
    outs = [o async for o in engine.generate(req, Context())]
    assert [t for o in outs for t in o["token_ids"]] == [97, 98, 99, 100]
    assert outs[-1]["finish_reason"] == "stop"

    # EOS finishes unless ignore_eos; min_tokens defers it.
    engine = MockTpuEngine(FAST, eos_token_ids=(ord("b"),))
    req = PreprocessedRequest(
        model="mock",
        token_ids=[1] * 10,
        stop=StopConditions(max_tokens=20),
        request_id="eos1",
    ).to_wire()
    outs = [o async for o in engine.generate(req, Context())]
    assert outs[-1]["finish_reason"] == "eos"
    assert [t for o in outs for t in o["token_ids"]] == [97, 98]


async def test_engine_prefix_cache_hit_second_request():
    engine = MockTpuEngine(FAST)
    prompt = list(range(16))  # 4 full blocks
    out1 = [o async for o in engine.generate(make_request(prompt, 2, "a"), Context())]
    assert out1[0]["meta"]["cached_tokens"] == 0
    out2 = [o async for o in engine.generate(make_request(prompt, 2, "b"), Context())]
    assert out2[0]["meta"]["cached_tokens"] == 16  # all 4 blocks reused


async def test_engine_concurrent_requests_and_metrics():
    engine = MockTpuEngine(FAST)

    async def one(i):
        req = make_request([i] * 20, max_tokens=5, request_id=f"r{i}")
        return [o async for o in engine.generate(req, Context())]

    results = await asyncio.gather(*(one(i) for i in range(8)))
    assert all(sum(len(o["token_ids"]) for o in r) == 5 for r in results)
    m = engine.metrics()
    assert m.worker.request_active_slots == 0
    assert m.kv.kv_total_blocks == 64


async def test_engine_emits_kv_events():
    stored = []
    engine = MockTpuEngine(FAST)
    engine.kv.on_stored = lambda h, p: stored.extend(h)
    prompt = list(range(12))  # 3 blocks
    [o async for o in engine.generate(make_request(prompt, 5), Context())]
    want = compute_seq_hashes(prompt, 4)
    assert stored[: len(want)] == want  # prompt blocks stored in chain order
    # decode added 12+5=17 tokens → 4 complete blocks total
    assert len(stored) == 4


async def test_engine_cancellation_frees_blocks():
    engine = MockTpuEngine(FAST)
    ctx = Context()
    gen = engine.generate(make_request([1] * 40, max_tokens=1000), ctx)
    got = 0
    async for _ in gen:
        got += 1
        if got == 3:
            ctx.stop_generating()
    assert got < 1000
    for _ in range(200):
        if engine.kv.free_blocks == engine.kv.capacity:
            break
        await asyncio.sleep(0.01)
    # All blocks released (inactive LRU still holds hashes but is reclaimable)
    assert engine.kv.free_blocks == engine.kv.capacity
