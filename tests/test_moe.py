"""Sparse MoE: routing math, dense equivalence, EP sharding, engine e2e."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dynamo_tpu.engine import EngineCore, tiny_engine
from dynamo_tpu.engine.config import ModelConfig, tiny_moe
from dynamo_tpu.engine.model import (
    _mlp,
    _moe_mlp,
    fuse_gu,
    init_cache,
    init_params,
)
from dynamo_tpu.parallel.sharding import cache_sharding, make_mesh, shard_params
from tests.model_harness import prefill_chunk
from tests.test_engine_core import _req, run_to_completion

MOE = tiny_moe()


def test_moe_reduces_to_dense_with_identical_experts():
    """top_k == num_experts with identical experts == the dense MLP."""
    cfg = ModelConfig(
        name="t", vocab_size=64, hidden_size=16, intermediate_size=32,
        num_layers=1, num_heads=2, num_kv_heads=2, head_dim=8, dtype="float32",
        num_experts=4, num_experts_per_tok=4, tie_embeddings=True,
    )
    rng = jax.random.PRNGKey(0)
    w_gate = jax.random.normal(rng, (16, 32)) * 0.1
    w_up = jax.random.normal(jax.random.fold_in(rng, 1), (16, 32)) * 0.1
    w_down = jax.random.normal(jax.random.fold_in(rng, 2), (32, 16)) * 0.1
    dense_w = {"wgu": fuse_gu(w_gate, w_up), "w_down": w_down}
    moe_lp = {
        "w_router": jnp.zeros((16, 4)),  # uniform routing
        "w_gate": jnp.tile(w_gate[None], (4, 1, 1)),
        "w_up": jnp.tile(w_up[None], (4, 1, 1)),
        "w_down": jnp.tile(w_down[None], (4, 1, 1)),
    }
    x = jax.random.normal(jax.random.fold_in(rng, 3), (6, 16))
    dense_cfg = ModelConfig(
        name="d", vocab_size=64, hidden_size=16, intermediate_size=32,
        num_layers=1, num_heads=2, num_kv_heads=2, head_dim=8, dtype="float32",
        tie_embeddings=True,
    )
    want = _mlp(x, dense_w, dense_cfg, tp=1)
    got = _moe_mlp(x, moe_lp, cfg)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-5)


def test_moe_top_k_sparsity():
    """Only top-k experts receive nonzero weight."""
    cfg = tiny_moe()
    rng = jax.random.PRNGKey(1)
    params = init_params(rng, cfg)
    lp = jax.tree.map(lambda a: a[0], params["layers"])  # layer 0 slice
    x = jax.random.normal(rng, (5, cfg.hidden_size))
    router = jnp.dot(x, lp["w_router"])
    _, idx = jax.lax.top_k(router, cfg.num_experts_per_tok)
    out = _moe_mlp(x, lp, cfg)
    assert out.shape == x.shape
    assert int(idx.shape[1]) == 2


def test_moe_engine_generates_end_to_end():
    core = EngineCore(MOE, tiny_engine(), seed=0)
    seq = core.add_request(_req(list(range(2, 30)), "moe1", max_tokens=6))
    done, fin = run_to_completion(core, [seq])
    assert len(done["moe1"]) == 6
    assert fin["moe1"] == "length"
    # Greedy determinism across engines.
    core2 = EngineCore(MOE, tiny_engine(), seed=0)
    seq2 = core2.add_request(_req(list(range(2, 30)), "moe2", max_tokens=6))
    done2, _ = run_to_completion(core2, [seq2])
    assert done2["moe2"] == done["moe1"]


@pytest.mark.slow  # heaviest moe compile; tier-1 keeps the alltoall/e2e cells
def test_moe_expert_parallel_matches_single_device():
    eng = tiny_engine()
    prompt = list(np.arange(1, 21))
    blocks = [0, 1, 2, 3]

    params1 = init_params(jax.random.PRNGKey(2), MOE, tp=1)
    want, _ = prefill_chunk(
        params1, init_cache(MOE, eng), prompt, 0, blocks, MOE, eng, 32
    )

    mesh = make_mesh(dp=2, tp=2)  # ep rides the tp axis: 4 experts / 2
    params2 = init_params(jax.random.PRNGKey(2), MOE, tp=2)
    sp = shard_params(params2, MOE, mesh)
    cd = jax.device_put(init_cache(MOE, eng), cache_sharding(mesh))
    got, _ = prefill_chunk(sp, cd, prompt, 0, blocks, MOE, eng, 32, mesh=mesh)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-4, atol=1e-4)


def _dense_moe_reference(x, lp, cfg):
    """All-experts dense dispatch (the pre-round-4 implementation), kept
    as ground truth for the sparse gather/scatter path."""
    xf = x.reshape(-1, x.shape[-1])
    N = xf.shape[0]
    router = jnp.dot(xf, lp["w_router"], preferred_element_type=jnp.float32)
    vals, idx = jax.lax.top_k(router, cfg.num_experts_per_tok)
    probs = jax.nn.softmax(vals, axis=-1)
    weights = jnp.zeros_like(router).at[jnp.arange(N)[:, None], idx].set(probs)
    gate = jnp.einsum("nh,ehi->nei", xf, lp["w_gate"], preferred_element_type=jnp.float32)
    up = jnp.einsum("nh,ehi->nei", xf, lp["w_up"], preferred_element_type=jnp.float32)
    act = (jax.nn.silu(gate) * up).astype(x.dtype)
    down = jnp.einsum("nei,eih->neh", act, lp["w_down"], preferred_element_type=jnp.float32)
    return jnp.einsum("ne,neh->nh", weights, down).astype(x.dtype).reshape(x.shape)


def test_sparse_dispatch_matches_dense_reference():
    """With enough capacity, sparse gather/scatter dispatch is exact."""
    import dataclasses

    cfg = dataclasses.replace(tiny_moe(), moe_capacity_factor=float(tiny_moe().num_experts))
    rng = jax.random.PRNGKey(7)
    params = init_params(rng, cfg)
    lp = jax.tree.map(lambda a: a[0], params["layers"])
    x = jax.random.normal(jax.random.fold_in(rng, 1), (13, cfg.hidden_size))
    want = _dense_moe_reference(x, lp, cfg)
    got = _moe_mlp(x, lp, cfg)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-5)


def test_sparse_dispatch_flops_scale_with_top_k_not_num_experts():
    """Per-token expert-MLP FLOPs must follow top_k (x capacity factor),
    not num_experts — the point of sparse dispatch (VERDICT r3 #9)."""
    import dataclasses

    cfg = dataclasses.replace(
        tiny_moe(), num_experts=8, num_experts_per_tok=1, moe_capacity_factor=1.0
    )
    params = init_params(jax.random.PRNGKey(0), cfg)
    lp = jax.tree.map(lambda a: a[0], params["layers"])
    x = jnp.ones((32, cfg.hidden_size))

    def flops(fn):
        cost = jax.jit(fn).lower(x).compile().cost_analysis()
        if isinstance(cost, list):
            cost = cost[0]
        return float(cost["flops"])

    sparse = flops(lambda v: _moe_mlp(v, lp, cfg))
    dense = flops(lambda v: _dense_moe_reference(v, lp, cfg))
    # Dense computes all 8 experts per token; sparse only top-1 + padding.
    assert sparse < dense / 3, f"sparse {sparse} not ≪ dense {dense}"


def test_capacity_overflow_drops_tokens_not_correctness():
    """With capacity 1 and every token routed to one expert, outputs stay
    finite and shaped (dropped tokens contribute zero, GShard semantics)."""
    import dataclasses

    cfg = dataclasses.replace(tiny_moe(), moe_capacity_factor=0.01)
    params = init_params(jax.random.PRNGKey(3), cfg)
    lp = jax.tree.map(lambda a: a[0], params["layers"])
    x = jax.random.normal(jax.random.PRNGKey(4), (9, cfg.hidden_size))
    out = _moe_mlp(x, lp, cfg)
    assert out.shape == x.shape
    assert bool(jnp.all(jnp.isfinite(out)))


def test_alltoall_dispatch_matches_replicated_and_dense():
    """Token all-to-all EP dispatch (wide-EP mode, cfg.moe_dispatch=
    'alltoall') equals the replicated-dispatch path AND the dense
    reference on the same mesh with generous capacity (VERDICT r5 #7:
    both dispatch modes, identical outputs)."""
    import dataclasses

    cfg = dataclasses.replace(
        tiny_moe(), moe_capacity_factor=float(tiny_moe().num_experts)
    )
    rng = jax.random.PRNGKey(7)
    params = init_params(rng, cfg)
    lp = jax.tree.map(lambda a: a[0], params["layers"])
    # 14 tokens: NOT divisible by tp=2 — exercises the a2a pad path.
    x = jax.random.normal(jax.random.fold_in(rng, 1), (14, cfg.hidden_size))
    want = _dense_moe_reference(x, lp, cfg)

    mesh = make_mesh(dp=1, tp=2)
    from jax.sharding import NamedSharding, PartitionSpec as P

    lp_sharded = {
        "w_router": jax.device_put(lp["w_router"], NamedSharding(mesh, P())),
        "w_gate": jax.device_put(lp["w_gate"], NamedSharding(mesh, P("tp"))),
        "w_up": jax.device_put(lp["w_up"], NamedSharding(mesh, P("tp"))),
        "w_down": jax.device_put(lp["w_down"], NamedSharding(mesh, P("tp"))),
    }
    rep = _moe_mlp(x, lp_sharded, cfg, mesh=mesh)
    a2a_cfg = dataclasses.replace(cfg, moe_dispatch="alltoall")
    a2a = _moe_mlp(x, lp_sharded, a2a_cfg, mesh=mesh)

    np.testing.assert_allclose(np.asarray(rep), np.asarray(want), rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(a2a), np.asarray(want), rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(a2a), np.asarray(rep), rtol=1e-6, atol=1e-6)


def test_alltoall_engine_parity_with_single_device():
    """The REAL EngineCore in alltoall EP mode matches the single-device
    engine greedily (EP e2e for the wide-EP dispatch)."""
    import dataclasses

    cfg = dataclasses.replace(
        tiny_moe(), moe_capacity_factor=float(tiny_moe().num_experts)
    )

    def run(mesh, moe_dispatch):
        c = dataclasses.replace(cfg, moe_dispatch=moe_dispatch)
        core = EngineCore(c, tiny_engine(), seed=0, mesh=mesh)
        seqs = [
            core.add_request(_req(list(range(5 + i, 30 + i)), f"r{i}", max_tokens=5))
            for i in range(2)
        ]
        done, fins = run_to_completion(core, seqs)
        assert len(fins) == 2
        return done

    want = run(None, "replicated")
    got = run(make_mesh(dp=2, tp=2), "alltoall")
    assert got == want
