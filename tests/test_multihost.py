"""Multi-host engine: one global mesh over multiple processes.

The cluster-free validation the driver cannot do in-process: REAL
``jax.distributed`` with 2 CPU processes x 4 virtual devices forming one
dp=2 x tp=4 mesh (gloo collectives), with output parity against the
single-process engine — plus the leader/follower step-replication e2e
through the frontend. Reference parity: multi-node serving flags
``dist-init-addr / nnodes / node-rank``
(`components/backends/sglang/docs/multinode-examples.md:10`).
"""

import asyncio
import json
import os
import socket
import subprocess
import sys

import pytest

pytestmark = [pytest.mark.e2e, pytest.mark.pre_merge]

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _spawn(argv, **env_over):
    env = dict(os.environ, **env_over)
    env.pop("XLA_FLAGS", None)
    return subprocess.Popen(
        [sys.executable, *argv], cwd=REPO, env=env,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
    )


# The three subprocess tests below need jax.distributed with per-process
# CPU device counts (jax_num_cpu_devices), which this image's jax does not
# know — the children die at init and each test burns its spawn/timeout
# budget failing. Keep them out of tier-1 until the toolchain catches up;
# they run under the full (slow-inclusive) suite on capable environments.
@pytest.mark.slow
def test_two_process_mesh_matches_single_device(tmp_path):
    """2 processes x 4 CPU devices -> one dp=2 x tp=4 mesh; greedy tokens
    must equal the single-device engine's (VERDICT r5 #2 done-bar)."""
    coord = f"127.0.0.1:{_free_port()}"
    outs = [tmp_path / "r0.json", tmp_path / "r1.json"]
    procs = [
        _spawn(["tests/mh_child.py", coord, str(rank), str(outs[rank])])
        for rank in range(2)
    ]
    for p in procs:
        out, _ = p.communicate(timeout=300)
        assert p.returncode == 0, out.decode()[-3000:]

    got0 = json.loads(outs[0].read_text())
    got1 = json.loads(outs[1].read_text())
    assert got0 == got1, "ranks diverged"

    # Single-device reference (same seed = same model; this process has
    # its own 8-device CPU platform from conftest, mesh=None).
    from dynamo_tpu.engine.config import EngineConfig, ModelConfig
    from dynamo_tpu.engine.core import EngineCore
    from dynamo_tpu.llm.protocols.common import (
        PreprocessedRequest,
        SamplingOptions,
        StopConditions,
    )

    cfg = ModelConfig(
        name="dryrun", vocab_size=512, hidden_size=64, intermediate_size=128,
        num_layers=2, num_heads=8, num_kv_heads=8, head_dim=16,
        dtype="float32", tie_embeddings=True,
    )
    eng = EngineConfig(
        num_kv_blocks=32, block_size=8, max_num_seqs=8, max_model_len=128,
        prefill_buckets=(32, 64, 128), decode_buckets=(4, 8),
    )
    core = EngineCore(cfg, eng, seed=0)
    seqs = [
        core.add_request(
            PreprocessedRequest(
                model="t", token_ids=list(range(3 + i, 40 + i)),
                request_id=f"r{i}",
                sampling=SamplingOptions(temperature=0.0),
                stop=StopConditions(max_tokens=5),
            )
        )
        for i in range(3)
    ]
    want = {s.request_id: [] for s in seqs}
    fins = 0
    for _ in range(200):
        for seq, out in core.step():
            want[seq.request_id].extend(out.token_ids)
            if out.finish_reason:
                fins += 1
        if fins == 3:
            break
    assert got0 == want, "multi-process mesh diverged from single device"


@pytest.mark.slow
async def test_leader_follower_serving_e2e():
    """Full multi-host serving: a 2-process dp=2 x tp=2 pod (leader
    serves, follower replays step records over the store) behind the real
    frontend, output parity with a single-host worker."""
    import aiohttp

    from dynamo_tpu.frontend.main import run_frontend
    from dynamo_tpu.runtime import DistributedRuntime
    from dynamo_tpu.runtime.store import StoreServer

    async def chat(session, base_url, content, max_tokens=6):
        body = {
            "model": "mh", "messages": [{"role": "user", "content": content}],
            "max_tokens": max_tokens, "temperature": 0.0,
        }
        async with session.post(
            f"{base_url}/v1/chat/completions", json=body
        ) as resp:
            assert resp.status == 200, await resp.text()
            return await resp.json()

    store = StoreServer()
    await store.start()
    coord = f"127.0.0.1:{_free_port()}"
    workers = []
    try:
        for rank in range(2):
            workers.append(
                _spawn(
                    [
                        "-m", "dynamo_tpu.backends.jax",
                        "--model-name", "mh", "--preset", "tiny",
                        "--tp", "2", "--dp", "2",
                        "--nnodes", "2", "--node-rank", str(rank),
                        "--dist-init-addr", coord,
                        "--local-cpu-devices", "2",
                    ],
                    DYN_STORE_ADDRESS=store.address,
                )
            )

        front_rt = await DistributedRuntime.create(store.address)
        ready = asyncio.Event()
        services: list = []
        front = asyncio.create_task(
            run_frontend(
                front_rt, http_host="127.0.0.1", http_port=0,
                router_mode="round_robin", ready_event=ready,
                service_out=services,
            )
        )
        await asyncio.wait_for(ready.wait(), 15)
        base = f"http://127.0.0.1:{services[0].port}"
        async with aiohttp.ClientSession() as s:
            for _ in range(600):
                async with s.get(f"{base}/v1/models") as r:
                    if (await r.json())["data"]:
                        break
                await asyncio.sleep(0.1)
            else:
                raise TimeoutError("multihost model never appeared")

            out = await chat(s, base, "hello multihost")
            assert out["usage"]["completion_tokens"] == 6
            mh_text = out["choices"][0]["message"]["content"]
            # A second request proves lockstep survives (a desynced
            # follower deadlocks the leader's collectives instead).
            out2 = await chat(s, base, "hello multihost")
            assert out2["choices"][0]["message"]["content"] == mh_text

        front_rt.signal_shutdown()
        front.cancel()
        await front_rt.shutdown()
    finally:
        for p in workers:
            p.terminate()
        for p in workers:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()
        await store.stop()

    # Parity with a single-host worker cluster (same seed).
    from tests.test_e2e_jax_worker import JaxCluster, _chat as jx_chat

    async with JaxCluster() as c:
        async with aiohttp.ClientSession() as s:
            ref = await jx_chat(s, c.base_url, "hello multihost", max_tokens=6)
            assert ref["choices"][0]["message"]["content"] == mh_text


@pytest.mark.slow
def test_two_process_mesh_serves_hf_checkpoint(tmp_path):
    """Real weights across the pod: every rank loads the SAME HF
    checkpoint host-side (tp=4-fused), shard_params places each
    process's addressable shards onto the global dp=2 x tp=4 mesh, and
    greedy output matches a single-process engine serving the same
    checkpoint — the ``--model-path --nnodes N`` serving path."""
    torch = pytest.importorskip("torch")
    transformers = pytest.importorskip("transformers")

    hf_cfg = transformers.LlamaConfig(
        vocab_size=128, hidden_size=32, intermediate_size=64,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=4,
        max_position_embeddings=128, tie_word_embeddings=False,
    )
    torch.manual_seed(0)
    ckpt = tmp_path / "hf-mh"
    transformers.LlamaForCausalLM(hf_cfg).save_pretrained(ckpt)

    coord = f"127.0.0.1:{_free_port()}"
    outs = [tmp_path / "r0.json", tmp_path / "r1.json"]
    procs = [
        _spawn([
            "tests/mh_child.py", coord, str(rank), str(outs[rank]), str(ckpt)
        ])
        for rank in range(2)
    ]
    for p in procs:
        out, _ = p.communicate(timeout=300)
        assert p.returncode == 0, out.decode()[-3000:]
    got0 = json.loads(outs[0].read_text())
    assert got0 == json.loads(outs[1].read_text()), "ranks diverged"

    # Single-process reference on the SAME checkpoint (tp=1 load).
    import jax.numpy as jnp

    from dynamo_tpu.engine.config import EngineConfig
    from dynamo_tpu.engine.core import EngineCore
    from dynamo_tpu.engine.loader import load_hf_llama
    from dynamo_tpu.llm.protocols.common import (
        PreprocessedRequest,
        SamplingOptions,
        StopConditions,
    )

    cfg, params = load_hf_llama(ckpt, dtype=jnp.float32)
    eng = EngineConfig(
        num_kv_blocks=32, block_size=8, max_num_seqs=8, max_model_len=128,
        prefill_buckets=(32, 64, 128), decode_buckets=(4, 8),
    )
    core = EngineCore(cfg, eng, params=params, seed=0)
    seqs = [
        core.add_request(
            PreprocessedRequest(
                model="t", token_ids=list(range(3 + i, 40 + i)),
                request_id=f"r{i}",
                sampling=SamplingOptions(temperature=0.0),
                stop=StopConditions(max_tokens=5),
            )
        )
        for i in range(3)
    ]
    want = {s.request_id: [] for s in seqs}
    fins = 0
    for _ in range(200):
        for seq, out in core.step():
            want[seq.request_id].extend(out.token_ids)
            if out.finish_reason:
                fins += 1
        if fins == 3:
            break
    assert got0 == want, "checkpoint serving diverged across the pod"


def test_llama3_70b_v5e64_memory_plan():
    """The 70B north star is PLACEABLE: llama3-70b int8 on a v5e-64
    (16 hosts x 4 chips) as tp=8 x dp=8 — tp caps at num_kv_heads=8
    under the GQA sharding (parallel/sharding.py) — fits 16 GiB/chip
    with a serving KV pool, and the bf16 variant does NOT fit at tp=8
    (sanity that the plan actually constrains). BASELINE.md north star;
    placement math in parallel/placement.py from jax.eval_shape of the
    real init."""
    from dynamo_tpu.engine.config import EngineConfig, PRESETS
    from dynamo_tpu.parallel.placement import V5E_HBM_BYTES, memory_plan

    model = PRESETS["llama3-70b"]()
    # Serving pool: 2048 blocks x 32 tokens = 64k tokens of KV per replica.
    eng = EngineConfig(num_kv_blocks=1536, block_size=32, max_num_seqs=64,
                      max_model_len=8192)

    plan = memory_plan(model, eng, tp=8, dp=8, quant="int8")
    print("70b-int8 tp=8 x dp=8:", plan.describe())
    assert plan.fits(V5E_HBM_BYTES), plan.describe()
    # Params must dominate sanely: ~70 GB int8 / 8 chips + replicated
    # bf16 embeddings ~ 11 GiB.
    assert 8 * 1024**3 < plan.param_bytes_per_chip < 13 * 1024**3

    # bf16 70B at tp=8 (one host) must NOT fit — ~17.6 GiB of params/chip.
    bad = memory_plan(model, eng, tp=8, dp=8)
    assert not bad.fits(V5E_HBM_BYTES), bad.describe()

    # 8B int8 single chip (the shipping config) still fits.
    plan8 = memory_plan(
        PRESETS["llama3-8b"](),
        EngineConfig(num_kv_blocks=256, block_size=32, max_num_seqs=16,
                     max_model_len=4096),
        tp=1, quant="int8",
    )
    print("8b-int8 tp=1:", plan8.describe())
    assert plan8.fits(V5E_HBM_BYTES), plan8.describe()
