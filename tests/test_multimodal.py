"""Multimodal serving: processor split, patch-embed encoder, engine
splice, encoder-fleet descriptor handoff — e2e through the frontend.

Reference parity: `examples/multimodal/components/{processor,
encode_worker,worker}.py` (processor splits image refs; an encode worker
produces embeddings handed over by descriptor; the LLM worker consumes
them in place of the image's prompt positions).
"""

import asyncio
import base64

import aiohttp
import numpy as np
import pytest

from dynamo_tpu.llm.multimodal import (
    MM_PATCHES,
    image_bytes,
    patch_embed,
    pseudo_tokens,
    splice_pseudo_tokens,
    split_images,
)

pytestmark = [pytest.mark.pre_merge]


def data_url(payload: bytes) -> str:
    return "data:application/octet-stream;base64," + base64.b64encode(payload).decode()


IMG_A = data_url(b"a cat sitting on a red mat" * 9)
IMG_B = data_url(b"a dog running on green grass" * 9)


def test_processor_split_and_splice():
    messages = [
        {"role": "user", "content": [
            {"type": "text", "text": "what is in "},
            {"type": "image_url", "image_url": {"url": IMG_A}},
            {"type": "text", "text": " ?"},
        ]},
    ]
    out, refs = split_images(messages)
    assert refs == [IMG_A]
    assert "\x00img0\x00" in out[0]["content"]

    def encode(s: str) -> list[int]:
        return [b + 3 for b in s.encode()]  # byte-tokenizer-ish

    token_ids = encode(out[0]["content"])
    spliced, positions = splice_pseudo_tokens(token_ids, refs, 259, encode)
    (start, count), = positions
    assert count == MM_PATCHES
    assert spliced[start : start + count] == pseudo_tokens(IMG_A, 259)
    # Text around the image is untouched.
    assert spliced[:start] == encode("what is in ")
    assert spliced[start + count:] == encode(" ?")
    # Content-addressed: same image, same ids; different image, different.
    assert pseudo_tokens(IMG_A, 259) == pseudo_tokens(IMG_A, 259)
    assert pseudo_tokens(IMG_A, 259) != pseudo_tokens(IMG_B, 259)


def test_patch_embed_deterministic_and_content_sensitive():
    ea = patch_embed(image_bytes(IMG_A), hidden_size=64)
    assert ea.shape == (MM_PATCHES, 64) and ea.dtype == np.float32
    assert np.array_equal(ea, patch_embed(image_bytes(IMG_A), 64))
    assert not np.array_equal(ea, patch_embed(image_bytes(IMG_B), 64))


def test_engine_splices_image_embeddings():
    """Same text, different image -> different greedy output; same image
    twice -> identical output AND a prefix-cache hit (content-derived
    pseudo ids make the block hashes content-addressed)."""
    from dynamo_tpu.engine import EngineCore, tiny_engine, tiny_model
    from tests.test_engine_core import _req, run_to_completion

    cfg = tiny_model()

    def mm_request(rid, img):
        text = [5, 6, 7, 8]
        pseudo = pseudo_tokens(img, cfg.vocab_size)
        pre = _req(text + pseudo + [9, 10], rid, max_tokens=6)
        emb = patch_embed(image_bytes(img), cfg.hidden_size)
        pre.mm = {
            "images": [img],
            "positions": [[len(text), MM_PATCHES]],
            "embeds": emb.astype(np.float32).tobytes(),
            "embeds_shape": list(emb.shape),
        }
        return pre

    core = EngineCore(cfg, tiny_engine(), seed=0)
    a1, _ = run_to_completion(core, [core.add_request(mm_request("a1", IMG_A))])
    b1, _ = run_to_completion(core, [core.add_request(mm_request("b1", IMG_B))])
    assert a1["a1"] != b1["b1"], "image content did not influence output"

    seq = core.add_request(mm_request("a2", IMG_A))
    a2, _ = run_to_completion(core, [seq])
    assert a2["a2"] == a1["a1"]
    assert seq.num_cached_tokens > 0, "identical image missed the prefix cache"


async def _mm_chat(session, base_url, img_url, text="describe ", max_tokens=6):
    body = {
        "model": "tinyjax",
        "messages": [{
            "role": "user",
            "content": [
                {"type": "text", "text": text},
                {"type": "image_url", "image_url": {"url": img_url}},
            ],
        }],
        "max_tokens": max_tokens,
        "temperature": 0.0,
    }
    async with session.post(f"{base_url}/v1/chat/completions", json=body) as resp:
        assert resp.status == 200, await resp.text()
        return await resp.json()


async def test_multimodal_e2e_local_encode():
    """Chat with an image_url through the full stack (no encoder fleet:
    the worker encodes in-process). Different images yield different
    tokens; a repeated image prefix-hits (VERDICT r5 #6 done-bar)."""
    from tests.test_e2e_jax_worker import JaxCluster

    async with JaxCluster() as c:
        async with aiohttp.ClientSession() as s:
            oa = await _mm_chat(s, c.base_url, IMG_A)
            ob = await _mm_chat(s, c.base_url, IMG_B)
            assert oa["usage"]["completion_tokens"] == 6
            assert (
                oa["choices"][0]["message"]["content"]
                != ob["choices"][0]["message"]["content"]
            ), "image content did not influence the completion"
            oa2 = await _mm_chat(s, c.base_url, IMG_A)
            assert oa2["choices"][0]["message"] == oa["choices"][0]["message"]
            cached = oa2["usage"].get("prompt_tokens_details", {}).get(
                "cached_tokens", 0
            )
            assert cached > 0


async def test_multimodal_e2e_encoder_fleet():
    """With an encoder fleet deployed, the worker uses the descriptor
    handoff (encode -> embed_fetch) and the output matches the local-
    encode path exactly (same deterministic vision stand-in)."""
    from dynamo_tpu.backends.encoder.main import run_encode_worker
    from dynamo_tpu.runtime import DistributedRuntime
    from tests.test_e2e_jax_worker import JaxCluster

    async with JaxCluster() as c:
        enc_rt = await DistributedRuntime.create(c.store.address)
        c.runtimes.append(enc_rt)
        served = asyncio.Event()
        stats: list = []
        c.tasks.append(
            asyncio.create_task(
                run_encode_worker(
                    enc_rt, served_event=served, stats_out=stats
                )
            )
        )
        await asyncio.wait_for(served.wait(), 10)
        # The worker's encoder client watch needs a beat to see it.
        await asyncio.sleep(0.3)

        async with aiohttp.ClientSession() as s:
            out = await _mm_chat(s, c.base_url, IMG_A)
            assert out["usage"]["completion_tokens"] == 6
        assert stats[0]["encoded"] >= 1, "encoder fleet never encoded"
        assert stats[0]["fetched"] >= 1, "descriptor was never pulled"

    # Output parity with the local-encode path.
    async with JaxCluster() as c:
        async with aiohttp.ClientSession() as s:
            ref = await _mm_chat(s, c.base_url, IMG_A)
            assert (
                ref["choices"][0]["message"]["content"]
                == out["choices"][0]["message"]["content"]
            )


async def test_multimodal_request_through_mocker():
    """CI routing support: the mocker engine serves a multimodal request
    (pseudo tokens + mm fields ride the normal wire) without real
    embeddings — router/caching behavior stays testable GPU/TPU-free."""
    from tests.test_e2e_frontend import Cluster

    async with Cluster(num_workers=1) as c:
        async with aiohttp.ClientSession() as s:
            body = {
                "model": "mock",
                "messages": [{
                    "role": "user",
                    "content": [
                        {"type": "text", "text": "look: "},
                        {"type": "image_url", "image_url": {"url": IMG_A}},
                    ],
                }],
                "max_tokens": 5,
                "temperature": 0.0,
            }
            async with s.post(
                f"{c.base_url}/v1/chat/completions", json=body
            ) as resp:
                assert resp.status == 200, await resp.text()
                out = await resp.json()
            assert out["usage"]["completion_tokens"] == 5


async def test_multimodal_disaggregated_matches_aggregated():
    """Long multimodal prompts survive the P/D split: the work-queue
    payload is msgpack (raw embed bytes cannot ride json), the prefill
    fleet splices the same embeddings, and the output equals the
    aggregated path."""
    from tests.test_disagg import DisaggCluster
    from tests.test_e2e_jax_worker import JaxCluster

    long_text = "look closely at this picture and describe every detail "

    async def ask(base_url, s):
        body = {
            "model": "tinyjax",
            "messages": [{
                "role": "user",
                "content": [
                    {"type": "text", "text": long_text},
                    {"type": "image_url", "image_url": {"url": IMG_A}},
                ],
            }],
            "max_tokens": 6,
            "temperature": 0.0,
        }
        async with s.post(f"{base_url}/v1/chat/completions", json=body) as r:
            assert r.status == 200, await r.text()
            return await r.json()

    async with JaxCluster() as c:
        async with aiohttp.ClientSession() as s:
            want = await ask(c.base_url, s)

    async with DisaggCluster() as c:
        async with aiohttp.ClientSession() as s:
            got = await ask(c.base_url, s)
            assert got["choices"][0]["message"] == want["choices"][0]["message"]
            # The prompt is past the disagg threshold: the prefill fleet
            # actually served it (queue payload survived msgpack transit).
            assert c.prefill_core.iterations > 0
