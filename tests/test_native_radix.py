"""C++ radix index vs Python RadixTree: behavioral parity under fuzzing."""

import random

import pytest

from dynamo_tpu.llm.kv_router.indexer import RadixTree
from dynamo_tpu.llm.kv_router.protocols import KvCacheEvent, RouterEvent

native = pytest.importorskip("dynamo_tpu.llm.kv_router.native_radix")
if not native.native_available():
    pytest.skip("native toolchain unavailable", allow_module_level=True)

from dynamo_tpu.llm.kv_router.native_radix import NativeRadixTree  # noqa: E402


def stored(worker, eid, hashes, parent=None):
    return RouterEvent(worker, eid, KvCacheEvent(op="stored", block_hashes=tuple(hashes), parent_hash=parent))


def removed(worker, eid, hashes):
    return RouterEvent(worker, eid, KvCacheEvent(op="removed", block_hashes=tuple(hashes)))


def test_basic_parity():
    py, cc = RadixTree(), NativeRadixTree()
    chain = [101, 202, 303, 404]
    for t in (py, cc):
        t.apply_event(stored(1, 1, chain))
        t.apply_event(stored(2, 1, chain[:2]))
    assert cc.find_matches(chain) == py.find_matches(chain) == {1: 4, 2: 2}
    for t in (py, cc):
        t.apply_event(removed(1, 2, [202]))  # prunes 303/404 for worker 1
    assert cc.find_matches(chain) == py.find_matches(chain)
    assert cc.num_blocks() == py.num_blocks()


def test_event_id_dedup_parity():
    py, cc = RadixTree(), NativeRadixTree()
    for t in (py, cc):
        t.apply_event(stored(1, 5, [7, 8]))
        t.apply_event(removed(1, 5, [7]))   # same event id: ignored
    assert cc.find_matches([7, 8]) == py.find_matches([7, 8]) == {1: 2}


def test_remove_worker_parity():
    py, cc = RadixTree(), NativeRadixTree()
    for t in (py, cc):
        t.apply_event(stored(1, 1, [1, 2, 3]))
        t.apply_event(stored(2, 1, [1, 2]))
        t.remove_worker(1)
    assert cc.find_matches([1, 2, 3]) == py.find_matches([1, 2, 3]) == {2: 2}
    assert cc.num_blocks() == py.num_blocks() == 2


def test_dump_parity():
    py, cc = RadixTree(), NativeRadixTree()
    for t in (py, cc):
        t.apply_event(stored(3, 1, [11, 22, 33]))
    py_dump = {(e.event.block_hashes[0], e.event.parent_hash) for e in py.dump_as_events(3)}
    cc_dump = {(e.event.block_hashes[0], e.event.parent_hash) for e in cc.dump_as_events(3)}
    assert cc_dump == py_dump


def test_fuzz_parity():
    rng = random.Random(42)
    py, cc = RadixTree(), NativeRadixTree()
    eid = {w: 0 for w in range(4)}
    chains = [[rng.getrandbits(63) for _ in range(rng.randint(1, 10))] for _ in range(20)]
    for step in range(400):
        w = rng.randrange(4)
        eid[w] += 1
        chain = rng.choice(chains)
        cut = rng.randint(1, len(chain))
        if rng.random() < 0.6:
            ev = stored(w, eid[w], chain[:cut])
        elif rng.random() < 0.9:
            ev = removed(w, eid[w], rng.sample(chain, min(len(chain), rng.randint(1, 3))))
        else:
            py.remove_worker(w)
            cc.remove_worker(w)
            continue
        py.apply_event(ev)
        cc.apply_event(ev)
        if step % 20 == 0:
            probe = rng.choice(chains)
            assert cc.find_matches(probe) == py.find_matches(probe), f"step {step}"
    for w in range(4):
        assert cc.num_blocks(w) == py.num_blocks(w)
