"""Fleet observability plane (ISSUE 13).

Covers the three parts end to end on real runtime fixtures:

- snapshot wire + publisher/aggregator over a real store, including the
  retirement triad: drain retraction (`retired` snapshot), lease-loss
  (instance watch), and staleness — dead workers' series are REMOVED
  from the fleet /metrics, never zeroed;
- the aggregator lifecycle e2e on a 3-worker mocker fleet (one drained,
  one killed) with planner Observations fed from live workers only;
- per-tenant SLO attribution (phase scanning, frontend+worker merge,
  the tenant cardinality cap) and the embedded-frontend /fleet page;
- the flight recorder: bounded ring, redaction contract, and the
  chaos-kill / stall-deadline dumps whose step records reconstruct the
  victim's committed stream;
- the tuned trace-phase histogram buckets (satellite pin).
"""

import asyncio
import json
import time
from contextlib import suppress

import pytest

from dynamo_tpu import tracing
from dynamo_tpu.llm.mocker import MockEngineArgs, MockTpuEngine
from dynamo_tpu.llm.protocols.common import (
    PreprocessedRequest,
    SamplingOptions,
    StopConditions,
)
from dynamo_tpu.obs import flight_recorder
from dynamo_tpu.obs.aggregator import FleetAggregator
from dynamo_tpu.obs.flight_recorder import FlightRecorder
from dynamo_tpu.obs.slo import (
    FRONTEND_COMPLETE_ON,
    FRONTEND_PHASES,
    PhaseScanner,
    SloAttributor,
    SloTargets,
)
from dynamo_tpu.obs.snapshot import MetricSnapshot, SnapshotPublisher
from dynamo_tpu.runtime import DistributedRuntime, chaos
from dynamo_tpu.runtime.chaos import ChaosPlan, ChaosRule
from dynamo_tpu.runtime.engine import Context
from dynamo_tpu.runtime.metrics import MetricsRegistry
from dynamo_tpu.runtime.store import StoreServer
from dynamo_tpu.tracing.core import _PHASE_BUCKETS, TraceCollector

pytestmark = [pytest.mark.integration, pytest.mark.pre_merge]


@pytest.fixture(autouse=True)
def _fresh_flight_state():
    """A process-wide dump flushes EVERY registered ring — engines leaked
    (but still referenced) by earlier suites in the same pytest process
    would dump alongside this module's victims, so each test starts from
    an empty registry and budget."""
    flight_recorder.reset_budget()
    flight_recorder.reset_registry()
    yield


def make_req(rid: str, max_tokens: int = 8, tenant: str = "") -> dict:
    pre = PreprocessedRequest(
        model="mock",
        token_ids=[1, 2, 3, 4],
        request_id=rid,
        sampling=SamplingOptions(),
        stop=StopConditions(max_tokens=max_tokens),
    )
    if tenant:
        pre.tenant_id = tenant
    return pre.to_wire()


def snap(wid: int, seq: int, **kw) -> MetricSnapshot:
    return MetricSnapshot(worker_id=wid, seq=seq, t=time.time(), **kw)


def dump_for_rid(paths, rid: str) -> dict:
    """The flight artifact whose step records carry this request's lane
    cursors (a process-wide dump writes one artifact per live ring)."""
    for p in paths:
        payload = json.loads(p.read_text())
        if any(
            lane.get("rid") == rid
            for r in payload["records"]
            for lane in r.get("lanes", [])
        ):
            return payload
    raise AssertionError(f"no dump in {[str(p) for p in paths]} carries {rid!r}")


# ---------------------------------------------------------------------------
# Wire + buckets
# ---------------------------------------------------------------------------


def test_snapshot_wire_roundtrip():
    s = MetricSnapshot(
        worker_id=42,
        role="worker",
        component="backend",
        seq=7,
        t=123.5,
        families={"scheduler": {"waiting": 3.0, "running": 2.0}},
        tenants={"acme": {"depth": 1.0, "deficit": 16.0}},
        phases={"engine/prefill": (4.0, 0.25)},
        requests=[{"rid": "r1", "tenant": "acme", "phases": {"prefill": 0.1}}],
    )
    back = MetricSnapshot.from_wire(s.to_wire())
    assert back == s
    retired = MetricSnapshot(worker_id=42, retired=True)
    assert MetricSnapshot.from_wire(retired.to_wire()).retired


def test_phase_buckets_cover_measured_ranges():
    """Satellite pin: the trace-phase histogram edges resolve sub-ms
    decode iterations AND multi-second prefills — a p99 estimated off
    /metrics must interpolate inside a bucket, not saturate the top."""
    assert list(_PHASE_BUCKETS) == sorted(set(_PHASE_BUCKETS)), "monotonic"
    # Sub-ms resolution for decode iterations / host_gap stats.
    assert _PHASE_BUCKETS[0] <= 1e-4
    assert sum(1 for b in _PHASE_BUCKETS if b < 1e-3) >= 4
    # Multi-second prefill resolution: several edges between 1 s and the
    # top, and a top edge well past the longest chunked prefill.
    assert sum(1 for b in _PHASE_BUCKETS if 1.0 <= b < _PHASE_BUCKETS[-1]) >= 6
    assert _PHASE_BUCKETS[-1] >= 60.0


def test_collector_phase_totals_accumulate():
    collector = TraceCollector(capacity=8)
    tracer = tracing.Tracer("svc", collector)
    for _ in range(20):  # more spans than ring capacity: totals survive
        tracer.record("phase_x", 1.0, 1.5)
    count, total = collector.phase_totals()["svc/phase_x"]
    assert count == 20 and abs(total - 10.0) < 1e-9


# ---------------------------------------------------------------------------
# Flight recorder
# ---------------------------------------------------------------------------


def test_flight_recorder_ring_bounded_and_redacted(tmp_path, monkeypatch):
    monkeypatch.setenv("DYN_FLIGHT_DIR", str(tmp_path))
    flight_recorder.reset_budget()
    rec = FlightRecorder("unit", capacity=4)
    for i in range(10):
        rec.record_step(i=i, emitted=1, token_ids=[1, 2, 3], text="secret")
    rec.record_event("shed_queue_full", rid="r9", prompt="user secret")
    records = rec.snapshot()
    assert len(records) == 4  # bounded ring
    paths = flight_recorder.dump_all("sigterm_drain", "unit-test")
    assert len(paths) == 1
    payload = json.loads(open(paths[0]).read())
    assert payload["reason"] == "sigterm_drain"
    dumped = json.dumps(payload)
    # Redaction contract: payload-bearing keys never reach the artifact.
    assert "token_ids" not in dumped
    assert "secret" not in dumped
    assert payload["records"][-1]["event"] == "shed_queue_full"
    # Budget: immediate same-reason re-dump is coalesced by the cooldown.
    assert flight_recorder.dump_all("sigterm_drain") == []


def test_flight_recorder_capacity_zero_disables():
    rec = FlightRecorder("off", capacity=0)
    rec.record_step(i=1)
    rec.record_event("x")
    assert rec.snapshot() == []


async def test_chaos_kill_dump_reconstructs_committed_stream(
    tmp_path, monkeypatch
):
    """Acceptance: a chaos kill produces a flight-recorder dump whose
    step records match the victim's committed stream — cumulative
    per-lane emitted counts equal the tokens the client received, and
    the megastep shape is reconstructable."""
    monkeypatch.setenv("DYN_FLIGHT_DIR", str(tmp_path))
    flight_recorder.reset_budget()
    engine = MockTpuEngine(
        MockEngineArgs(
            num_kv_blocks=256, block_size=8, megastep_k=4,
            speedup_ratio=200.0,
        )
    )
    engine.chaos_tag = "victim"
    chaos.install(
        ChaosPlan(
            [ChaosRule(point="engine.step", action="kill", match="victim",
                       after=6)]
        )
    )
    received = 0
    try:
        gen = engine.generate(make_req("r-kill", max_tokens=64), Context())
        with suppress(asyncio.TimeoutError):
            while True:
                # The kill parks the stream; the timeout is how the test
                # observes "worker died mid-decode".
                out = await asyncio.wait_for(gen.__anext__(), 1.0)
                received += len(out.get("token_ids") or [])
    finally:
        chaos.uninstall()
    assert engine._dead and received > 0
    dumps = sorted(tmp_path.glob("flight-*chaos_kill*.json"))
    assert dumps, "chaos kill left no flight-recorder artifact"
    payload = dump_for_rid(dumps, "r-kill")
    assert payload["reason"] == "chaos_kill"
    steps = [r for r in payload["records"] if r.get("kind") == "step"]
    assert steps, "no step records in the dump"
    emitted = sum(
        lane.get("emitted", 0)
        for r in steps
        for lane in r.get("lanes", [])
        if lane.get("rid") == "r-kill"
    )
    cursors = [
        lane["generated"]
        for r in steps
        for lane in r.get("lanes", [])
        if lane.get("rid") == "r-kill" and "generated" in lane
    ]
    # The dump reconstructs the committed stream: per-step emissions sum
    # to exactly what the client saw, and the final lane cursor agrees.
    assert emitted == received
    assert cursors and cursors[-1] == received
    # The victim's final megasteps are reconstructable (k > 1 fused).
    assert any(r.get("k", 1) > 1 for r in steps)
    assert "token_ids" not in json.dumps(payload)  # redacted


async def test_stall_deadline_dump_captures_victim_steps(
    tmp_path, monkeypatch
):
    """Acceptance: a stall-deadline fire produces a dump whose step
    records match the victim's committed stream (single-process fleet:
    the client-side stall trigger flushes the wedged engine's ring)."""
    monkeypatch.setenv("DYN_FLIGHT_DIR", str(tmp_path))
    flight_recorder.reset_budget()
    store = StoreServer()
    await store.start()
    rt = await DistributedRuntime.create(store.address)
    engine = MockTpuEngine(
        MockEngineArgs(num_kv_blocks=256, block_size=8, speedup_ratio=50.0)
    )
    engine.chaos_tag = "w-stall"
    ep = rt.namespace("obs").component("w").endpoint("generate")

    async def handler(req, ctx):
        async for out in engine.generate(req, ctx):
            yield out

    await ep.serve(handler)
    client_rt = await DistributedRuntime.create(store.address)
    client_rt.egress.policy.stall_s = 0.5
    client = await (
        client_rt.namespace("obs").component("w").endpoint("generate").client()
    )
    await client.wait_for_instances(1, timeout=10)
    chaos.install(
        ChaosPlan(
            [ChaosRule(point="engine.step", action="stall", match="w-stall",
                       after=4, stall_s=3600.0)]
        )
    )
    received = 0
    try:
        stream = await client.round_robin(make_req("r-stall", max_tokens=64))
        with suppress(ConnectionError):
            async for out in stream:
                received += len(out.get("token_ids") or [])
    finally:
        chaos.uninstall()
        await client.stop()
        await client_rt.shutdown()
        with suppress(ConnectionError, OSError):
            await rt.shutdown()
        await store.stop()
    assert received > 0
    dumps = sorted(tmp_path.glob("flight-*stall_deadline*.json"))
    assert dumps, "stall deadline left no flight-recorder artifact"
    payload = dump_for_rid(dumps, "r-stall")
    steps = [r for r in payload["records"] if r.get("kind") == "step"]
    emitted = sum(
        lane.get("emitted", 0)
        for r in steps
        for lane in r.get("lanes", [])
        if lane.get("rid") == "r-stall"
    )
    assert emitted == received


# ---------------------------------------------------------------------------
# SLO attribution
# ---------------------------------------------------------------------------


def test_phase_scanner_groups_request_spans():
    collector = TraceCollector(capacity=64)
    tracer = tracing.Tracer("engine", collector)
    scanner = PhaseScanner(collector)
    tracer.record("sched_admit", 1.0, 1.02,
                  attrs={"request_id": "r1", "tenant": "acme"})
    tracer.record("prefill", 1.0, 1.10,
                  attrs={"request_id": "r1", "tenant": "acme"})
    assert scanner.scan() == []  # decode not seen yet: still open
    tracer.record("decode", 1.10, 1.50,
                  attrs={"request_id": "r1", "tokens": 9, "tenant": "acme"})
    records = scanner.scan()
    assert len(records) == 1
    rec = records[0]
    assert rec["rid"] == "r1" and rec["tenant"] == "acme"
    assert rec["tokens"] == 9
    assert abs(rec["phases"]["prefill"] - 0.10) < 1e-9
    assert scanner.scan() == []  # already consumed


def test_slo_attributor_merges_and_caps_tenants():
    att = SloAttributor(
        targets=SloTargets(ttft_s=0.2, tpot_s=0.05), grace_s=60.0,
        max_tenants=4,
    )
    att.ingest(
        [{"rid": "r1", "tenant": "acme", "tokens": 11,
          "phases": {"sched_admit": 0.02, "prefill": 0.10, "decode": 0.50}}],
        side="worker",
    )
    att.ingest(
        [{"rid": "r1", "tenant": "acme",
          "phases": {"http": 0.70, "tokenize": 0.01, "route": 0.02}}],
        side="frontend",
    )
    s = att.summary()
    acme = s["tenants"]["acme"]
    assert acme["requests"] == 1
    # ttft = tokenize + route + prefill = 0.13 s; tpot = 0.5/10 = 50 ms.
    assert abs(acme["ttft_p50_ms"] - 130.0) < 1.0
    assert abs(acme["tpot_p50_ms"] - 50.0) < 0.5
    assert acme["ttft_attainment"] == 1.0
    assert acme["phase_mean_ms"]["queue"] == 20.0
    # Duplicate delivery (snapshot redeliver) must not double-count.
    att.ingest(
        [{"rid": "r1", "tenant": "acme", "tokens": 11,
          "phases": {"prefill": 0.10, "decode": 0.50}}],
        side="worker",
    )
    assert att.summary()["tenants"]["acme"]["requests"] == 1
    # Cardinality cap: tenants beyond max land in __other__.
    for i in range(10):
        att.ingest(
            [{"rid": f"t{i}", "tenant": f"tenant-{i}", "tokens": 2,
              "phases": {"prefill": 0.01, "decode": 0.01}}],
            side="worker",
        )
    att.sweep(time.monotonic() + 120.0)  # force worker-only finalize
    tenants = set(att.summary()["tenants"])
    assert len(tenants) <= 5  # 4 tracked + __other__
    assert "__other__" in tenants


# ---------------------------------------------------------------------------
# Aggregator: export, rollups, retirement, tenant cap
# ---------------------------------------------------------------------------


def _bound_aggregator(**kw):
    agg = FleetAggregator(store=None, namespace="dynamo", **kw)
    registry = MetricsRegistry()
    hooks: list = []
    agg.bind(registry, hooks)
    return agg, registry, hooks


def test_aggregator_exports_worker_series_and_rollups():
    agg, registry, hooks = _bound_aggregator(stale_after_s=60.0)
    agg.ingest(snap(1, 1, families={"scheduler": {"waiting": 3.0}}))
    agg.ingest(snap(2, 1, families={"scheduler": {"waiting": 7.0}}))
    hooks[0]()
    text = registry.render().decode()
    assert 'dynamo_scheduler_waiting_seqs{namespace="dynamo",service="engine",worker_id="1"} 3.0' in text
    assert 'dynamo_scheduler_waiting_seqs{namespace="dynamo",service="engine",worker_id="2"} 7.0' in text
    assert 'dynamo_fleet_scheduler_waiting_seqs{namespace="dynamo",service="engine",stat="sum"} 10.0' in text
    assert 'stat="max"} 7.0' in text
    # Retirement removes the series (not zeroed) and rollups follow.
    agg.ingest(MetricSnapshot(worker_id=2, retired=True))
    hooks[0]()
    text = registry.render().decode()
    assert 'worker_id="2"' not in text
    assert 'dynamo_fleet_scheduler_waiting_seqs{namespace="dynamo",service="engine",stat="sum"} 3.0' in text
    assert agg.workers_retired_total == 1
    # The LAST contributor retiring removes the rollups too — never
    # frozen at the dead fleet's final values (the empty family keeps
    # its HELP/TYPE header; what matters is no sample remains).
    agg.ingest(MetricSnapshot(worker_id=1, retired=True))
    hooks[0]()
    text = registry.render().decode()
    assert not [
        ln for ln in text.splitlines()
        if ln.startswith("dynamo_fleet_scheduler_waiting_seqs{")
    ]


def test_aggregator_staleness_retires_series():
    agg, registry, hooks = _bound_aggregator(stale_after_s=0.2)
    agg.ingest(snap(5, 1, families={"scheduler": {"waiting": 1.0}}))
    hooks[0]()
    assert 'worker_id="5"' in registry.render().decode()
    time.sleep(0.25)
    hooks[0]()
    assert 'worker_id="5"' not in registry.render().decode()
    assert agg.live_workers() == []


def test_aggregator_staleness_ignores_publisher_clock_skew():
    """Staleness is judged on the AGGREGATOR's arrival clock: a worker
    whose own wall clock is far behind (t stamped minutes ago) keeps
    publishing and must stay in the fleet view."""
    agg, _registry, _hooks = _bound_aggregator(stale_after_s=0.5)
    skewed = MetricSnapshot(
        worker_id=3, seq=1, t=time.time() - 3600.0,
        families={"scheduler": {"waiting": 1.0}},
    )
    agg.ingest(skewed)
    assert agg.sweep_stale() == []
    assert agg.live_workers() == [3]


def test_aggregator_accepts_restarted_publisher_epoch():
    """A publisher that restarts with the SAME worker_id starts seq over
    at 1 under a new epoch — its fresh snapshots must replace the dead
    incarnation immediately, not be dropped as out-of-order until the
    staleness sweep."""
    agg, _registry, _hooks = _bound_aggregator(stale_after_s=60.0)
    agg.ingest(snap(4, 7, epoch=100.0, families={"scheduler": {"waiting": 9.0}}))
    # Same-incarnation redelivery of an older seq: dropped.
    agg.ingest(snap(4, 6, epoch=100.0, families={"scheduler": {"waiting": 1.0}}))
    assert agg.latest[4].families["scheduler"]["waiting"] == 9.0
    # Restarted incarnation, seq reset: accepted at once.
    agg.ingest(snap(4, 1, epoch=200.0, families={"scheduler": {"waiting": 2.0}}))
    assert agg.latest[4].seq == 1
    assert agg.latest[4].families["scheduler"]["waiting"] == 2.0


def test_aggregator_tenant_cardinality_cap():
    """Satellite pin: adversarial x-tenant-id churn cannot grow the
    aggregator /metrics unboundedly — 64 series + __other__, retired
    tenants removed."""
    agg, registry, hooks = _bound_aggregator(stale_after_s=60.0)
    tenants = {
        f"tenant-{i:03d}": {"depth": float(i), "deficit": 1.0}
        for i in range(100)
    }
    agg.ingest(snap(1, 1, tenants=tenants))
    hooks[0]()
    text = registry.render().decode()
    depth_series = [
        ln for ln in text.splitlines()
        if ln.startswith("dynamo_fleet_tenant_queue_depth{")
    ]
    assert len(depth_series) == 65  # 64 + __other__
    assert any('tenant="__other__"' in ln for ln in depth_series)
    # Tenants drain away -> their series leave with them.
    agg.ingest(snap(1, 2, tenants={"tenant-099": {"depth": 1.0, "deficit": 0.0}}))
    hooks[0]()
    text = registry.render().decode()
    depth_series = [
        ln for ln in text.splitlines()
        if ln.startswith("dynamo_fleet_tenant_queue_depth{")
    ]
    assert len(depth_series) == 1 and 'tenant="tenant-099"' in depth_series[0]


def test_aggregator_observation_diffs_frontend_and_phases():
    agg, _registry, _hooks = _bound_aggregator(stale_after_s=60.0)
    agg.ingest(
        snap(9, 1, role="frontend",
             families={"frontend": {
                 "requests_total": 10.0, "isl_sum": 2560.0, "isl_count": 10.0,
                 "osl_sum": 1280.0, "osl_count": 10.0,
                 "ttft_sum": 1.0, "ttft_count": 10.0,
                 "itl_sum": 0.5, "itl_count": 50.0,
             }},
             phases={"frontend/tokenize": (10.0, 0.1)})
    )
    first = agg.observation()
    assert first.request_rate == 0.0  # priming window
    agg.ingest(
        snap(9, 2, role="frontend",
             families={"frontend": {
                 "requests_total": 20.0, "isl_sum": 5120.0, "isl_count": 20.0,
                 "osl_sum": 2560.0, "osl_count": 20.0,
                 "ttft_sum": 3.0, "ttft_count": 20.0,
                 "itl_sum": 1.5, "itl_count": 100.0,
             }},
             phases={"frontend/tokenize": (20.0, 0.3)})
    )
    obs = agg.observation()
    assert obs.request_rate > 0.0
    assert abs(obs.mean_isl - 256.0) < 1e-6
    assert abs(obs.observed_ttft_s - 0.2) < 1e-6
    assert abs(obs.observed_itl_s - 0.02) < 1e-6
    assert abs(obs.phase_means["tokenize"] - 0.02) < 1e-6


# ---------------------------------------------------------------------------
# Publisher + aggregator over a real store
# ---------------------------------------------------------------------------


async def test_snapshot_publisher_retire_over_store():
    store = StoreServer()
    await store.start()
    rt = await DistributedRuntime.create(store.address)
    agg_rt = await DistributedRuntime.create(store.address)
    agg = FleetAggregator(agg_rt.store, namespace="obs-t", stale_after_s=60.0)
    await agg.start()
    pub = SnapshotPublisher(
        rt.store, "obs-t", worker_id=77, component="backend",
        interval_s=0.03,
    )
    pub.collectors = {"scheduler": lambda: {"waiting": 4, "running": 1}}
    pub.tenant_source = lambda: {"acme": {"depth": 2.0, "deficit": 8.0}}
    try:
        await pub.start()
        for _ in range(100):
            if 77 in agg.latest:
                break
            await asyncio.sleep(0.02)
        assert agg.latest[77].families["scheduler"]["waiting"] == 4.0
        assert agg.latest[77].tenants["acme"]["depth"] == 2.0
        # Drain retraction: the retired snapshot removes the worker NOW.
        assert await pub.retire(timeout=5.0)
        for _ in range(100):
            if 77 not in agg.latest:
                break
            await asyncio.sleep(0.02)
        assert 77 not in agg.latest
    finally:
        await pub.stop()
        await agg.stop()
        await rt.shutdown()
        await agg_rt.shutdown()
        await store.stop()


async def test_snapshot_publisher_drain_survives_bad_publish():
    """A non-ConnectionError from one publish (bad payload, store-layer
    bug) must not kill the drain task: dying there strands ``_idle``
    cleared, so every later flush()/retire() would burn its full
    timeout. The failed snapshot is counted and the next one delivers."""

    class FlakyStore:
        def __init__(self):
            self.published = 0
            self.fail_next = True

        async def publish(self, subject, payload):
            if self.fail_next:
                self.fail_next = False
                raise ValueError("synthetic non-connection failure")
            self.published += 1

    store = FlakyStore()
    pub = SnapshotPublisher(store, "obs-t", worker_id=9, interval_s=60.0)
    pub.publish_nowait()
    pub.publish_nowait()
    assert await pub.flush(timeout=2.0), "drain task died on ValueError"
    assert store.published == 1
    assert pub.publish_errors_total == 1
    # The drain task is still alive and keeps delivering.
    pub.publish_nowait()
    assert await pub.flush(timeout=2.0)
    assert store.published == 2
    await pub.stop()


async def test_standalone_aggregator_service():
    """The reference `components/metrics` shape: one standalone process
    subscribing to the namespace's snapshots and serving the fleet
    /metrics + /fleet on its own status server."""
    import aiohttp

    from dynamo_tpu.obs.service import run_aggregator

    store = StoreServer()
    await store.start()
    rt = await DistributedRuntime.create(store.address)
    agg_rt = await DistributedRuntime.create(store.address)
    ready = asyncio.Event()
    statuses: list = []
    task = asyncio.create_task(
        run_aggregator(
            agg_rt, namespace="svc-t", host="127.0.0.1", port=0,
            ready_event=ready, status_out=statuses,
        )
    )
    pub = SnapshotPublisher(rt.store, "svc-t", worker_id=3, interval_s=0.03)
    pub.collectors = {"scheduler": lambda: {"waiting": 2, "running": 1}}
    try:
        await asyncio.wait_for(ready.wait(), 10)
        await pub.start()
        base = f"http://127.0.0.1:{statuses[0].port}"
        async with aiohttp.ClientSession() as s:
            text = ""
            for _ in range(100):
                async with s.get(f"{base}/metrics") as r:
                    assert r.status == 200
                    text = await r.text()
                if 'worker_id="3"' in text:
                    break
                await asyncio.sleep(0.05)
            assert 'worker_id="3"' in text
            assert "dynamo_fleet_scheduler_waiting_seqs" in text
            async with s.get(f"{base}/fleet") as r:
                assert r.status == 200
                payload = await r.json()
            assert payload["live_workers"] == [3]
            assert "slo" in payload
    finally:
        await pub.stop()
        task.cancel()
        with suppress(asyncio.CancelledError):
            await task
        await rt.shutdown()
        with suppress(ConnectionError, OSError):
            await agg_rt.shutdown()
        await store.stop()


# ---------------------------------------------------------------------------
# Fleet lifecycle e2e: 3 mocker workers, one drained, one killed
# ---------------------------------------------------------------------------


async def test_fleet_lifecycle_drain_kill_converge(tmp_path, monkeypatch):
    """Satellite e2e: 3 workers publish; one is killed (stops publishing
    — the staleness backstop retires it), one drains gracefully (the
    retired snapshot retires it immediately); the fleet view converges
    to the survivor, dead workers' series are REMOVED (not zeroed), and
    planner Observations come from live workers only."""
    from dynamo_tpu.backends.mocker.main import run_mocker

    monkeypatch.setenv("DYN_FLIGHT_DIR", str(tmp_path))
    flight_recorder.reset_budget()
    store = StoreServer()
    await store.start()
    runtimes, tasks = [], []
    for _ in range(3):
        rt = await DistributedRuntime.create(store.address)
        served = asyncio.Event()
        tasks.append(
            asyncio.create_task(
                run_mocker(
                    rt, model_name="mock",
                    engine_args=MockEngineArgs(
                        num_kv_blocks=256, block_size=8, speedup_ratio=50.0
                    ),
                    served_event=served, obs_interval_s=0.05,
                )
            )
        )
        await asyncio.wait_for(served.wait(), 20)
        runtimes.append(rt)
    wids = [rt.primary_lease_id for rt in runtimes]
    agg_rt = await DistributedRuntime.create(store.address)
    agg = FleetAggregator(agg_rt.store, namespace="dynamo", stale_after_s=0.6)
    registry = MetricsRegistry()
    hooks: list = []
    agg.bind(registry, hooks)
    await agg.start()
    client = await (
        agg_rt.namespace("dynamo").component("backend").endpoint("generate").client()
    )
    try:
        await client.wait_for_instances(3, timeout=10)
        # Traffic to every worker so phases + SLO records exist.
        for i, wid in enumerate(wids):
            stream = await client.direct(wid, make_req(f"warm-{i}"))
            async for _ in stream:
                pass
        for _ in range(200):
            if len(agg.live_workers()) == 3:
                break
            await asyncio.sleep(0.02)
        assert sorted(agg.live_workers()) == sorted(wids)
        hooks[0]()
        text = registry.render().decode()
        for wid in wids:
            assert f'worker_id="{wid}"' in text
        assert "dynamo_fleet_scheduler_running_seqs" in text

        # Graceful drain of worker 0: retired-snapshot retraction.
        await runtimes[0].drain(timeout=5.0)
        for _ in range(200):
            if wids[0] not in agg.live_workers():
                break
            await asyncio.sleep(0.02)
        assert wids[0] not in agg.live_workers()

        # Kill worker 1: cancel its serving task + drop its runtime
        # without drain — snapshots stop, staleness retires it.
        tasks[1].cancel()
        with suppress(ConnectionError, OSError):
            await runtimes[1].shutdown()
        deadline = time.monotonic() + 5.0
        while wids[1] in agg.live_workers() and time.monotonic() < deadline:
            agg.sweep_stale()
            await asyncio.sleep(0.1)
        assert agg.live_workers() == [wids[2]]

        hooks[0]()
        text = registry.render().decode()
        assert f'worker_id="{wids[0]}"' not in text  # removed, not zeroed
        assert f'worker_id="{wids[1]}"' not in text
        assert f'worker_id="{wids[2]}"' in text

        # Planner feed reflects only the live worker.
        agg.observation()  # prime the diff window
        stream = await client.direct(wids[2], make_req("post-kill"))
        async for _ in stream:
            pass
        await asyncio.sleep(0.2)  # one publish interval
        obs = agg.observation()
        assert obs.phase_means and "prefill" in obs.phase_means
        assert len(agg.latest) == 1
    finally:
        await client.stop()
        await agg.stop()
        for t in tasks:
            t.cancel()
        for rt in runtimes[2:] + [agg_rt]:
            with suppress(ConnectionError, OSError):
                await rt.shutdown()
        await store.stop()


# ---------------------------------------------------------------------------
# Embedded frontend: fleet /metrics + /fleet SLO page
# ---------------------------------------------------------------------------


async def test_frontend_embedded_fleet_and_slo(tmp_path, monkeypatch):
    import aiohttp

    from dynamo_tpu.backends.mocker.main import run_mocker
    from dynamo_tpu.frontend.main import run_frontend

    monkeypatch.setenv("DYN_FLIGHT_DIR", str(tmp_path))
    store = StoreServer()
    await store.start()
    runtimes, tasks = [], []
    for _ in range(2):
        rt = await DistributedRuntime.create(store.address)
        served = asyncio.Event()
        tasks.append(
            asyncio.create_task(
                run_mocker(
                    rt, model_name="mock",
                    engine_args=MockEngineArgs(
                        num_kv_blocks=256, block_size=8, speedup_ratio=50.0
                    ),
                    served_event=served, obs_interval_s=0.05,
                )
            )
        )
        await asyncio.wait_for(served.wait(), 20)
        runtimes.append(rt)
    front_rt = await DistributedRuntime.create(store.address)
    ready = asyncio.Event()
    services: list = []
    tasks.append(
        asyncio.create_task(
            run_frontend(
                front_rt, http_host="127.0.0.1", http_port=0,
                router_mode="round_robin", ready_event=ready,
                service_out=services, obs_interval_s=0.05,
            )
        )
    )
    await asyncio.wait_for(ready.wait(), 20)
    base = f"http://127.0.0.1:{services[0].port}"
    wids = [rt.primary_lease_id for rt in runtimes]
    try:
        async with aiohttp.ClientSession() as s:
            for _ in range(200):
                async with s.get(f"{base}/v1/models") as r:
                    if (await r.json())["data"]:
                        break
                await asyncio.sleep(0.05)
            body = {
                "model": "mock",
                "messages": [{"role": "user", "content": "hello fleet"}],
                "max_tokens": 6,
                "stream": False,
            }
            for i in range(4):  # round robin touches both workers
                async with s.post(
                    f"{base}/v1/chat/completions", json=body,
                    headers={"x-tenant-id": "acme"},
                ) as r:
                    assert r.status == 200, await r.text()
            # Fleet series with worker_id labels on the FRONTEND /metrics.
            deadline = time.monotonic() + 10.0
            text = ""
            while time.monotonic() < deadline:
                async with s.get(f"{base}/metrics") as r:
                    text = await r.text()
                if all(f'worker_id="{w}"' in text for w in wids):
                    break
                await asyncio.sleep(0.1)
            for w in wids:
                assert f'worker_id="{w}"' in text
            assert "dynamo_fleet_scheduler_running_seqs" in text
            # /fleet renders the per-tenant SLO breakdown.
            payload = {}
            while time.monotonic() < deadline:
                async with s.get(f"{base}/fleet") as r:
                    assert r.status == 200
                    payload = await r.json()
                slo = payload.get("dynamo", {}).get("slo", {})
                if slo.get("tenants", {}).get("acme", {}).get("requests"):
                    break
                await asyncio.sleep(0.1)
            fleet = payload["dynamo"]
            assert sorted(fleet["live_workers"]) == sorted(wids)
            acme = fleet["slo"]["tenants"]["acme"]
            assert acme["requests"] >= 1
            assert acme["ttft_p50_ms"] > 0
            assert "queue" in acme["phase_mean_ms"]
            # dynamo_slo_* histograms export per tenant.
            async with s.get(f"{base}/metrics") as r:
                text = await r.text()
            assert 'tenant="acme"' in text
            assert "dynamo_slo_ttft_seconds" in text
    finally:
        for t in tasks:
            t.cancel()
        for rt in runtimes + [front_rt]:
            with suppress(ConnectionError, OSError):
                await rt.shutdown()
        await store.stop()
