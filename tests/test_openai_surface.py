"""OpenAI surface completeness: /v1/embeddings, /v1/responses, TLS.

Parity: reference `lib/llm/src/http/service/service_v2.rs:277-336`
(embeddings/responses routes, TLS config).
"""

import asyncio
import ssl
import subprocess

import aiohttp
import pytest

from tests.test_e2e_jax_worker import JaxCluster

pytestmark = [pytest.mark.e2e, pytest.mark.pre_merge]


async def test_embeddings_endpoint():
    async with JaxCluster() as c:
        async with aiohttp.ClientSession() as s:
            body = {"model": "tinyjax", "input": "hello embedding world"}
            async with s.post(f"{c.base_url}/v1/embeddings", json=body) as r:
                assert r.status == 200, await r.text()
                out = await r.json()
            assert out["object"] == "list"
            vec = out["data"][0]["embedding"]
            assert len(vec) == 64  # tiny model hidden size
            assert out["usage"]["prompt_tokens"] > 0

            # Deterministic per input; batched inputs index correctly.
            async with s.post(f"{c.base_url}/v1/embeddings", json=body) as r:
                again = (await r.json())["data"][0]["embedding"]
            assert vec == again
            body2 = {"model": "tinyjax", "input": ["hello embedding world", "different"]}
            async with s.post(f"{c.base_url}/v1/embeddings", json=body2) as r:
                assert r.status == 200
                two = (await r.json())["data"]
            assert [d["index"] for d in two] == [0, 1]
            assert two[0]["embedding"] == vec
            assert two[1]["embedding"] != vec

            # Unknown model -> 404.
            async with s.post(
                f"{c.base_url}/v1/embeddings", json={"model": "nope", "input": "x"}
            ) as r:
                assert r.status == 404


async def test_responses_endpoint_matches_chat():
    async with JaxCluster() as c:
        async with aiohttp.ClientSession() as s:
            prompt = "say something"
            async with s.post(
                f"{c.base_url}/v1/responses",
                json={
                    "model": "tinyjax",
                    "input": prompt,
                    "max_output_tokens": 8,
                    "temperature": 0.0,
                },
            ) as r:
                assert r.status == 200, await r.text()
                resp = await r.json()
            assert resp["object"] == "response"
            assert resp["status"] == "completed"
            text = resp["output"][0]["content"][0]["text"]
            assert resp["usage"]["output_tokens"] == 8

            async with s.post(
                f"{c.base_url}/v1/chat/completions",
                json={
                    "model": "tinyjax",
                    "messages": [{"role": "user", "content": prompt}],
                    "max_tokens": 8,
                    "temperature": 0.0,
                },
            ) as r:
                chat = await r.json()
            assert text == chat["choices"][0]["message"]["content"]

            # Message-list input works too.
            async with s.post(
                f"{c.base_url}/v1/responses",
                json={
                    "model": "tinyjax",
                    "input": [{"role": "user", "content": prompt}],
                    "max_output_tokens": 4,
                },
            ) as r:
                assert r.status == 200
            # Missing input -> 400.
            async with s.post(
                f"{c.base_url}/v1/responses", json={"model": "tinyjax"}
            ) as r:
                assert r.status == 400


async def test_tls_serves_https(tmp_path):
    cert = tmp_path / "cert.pem"
    key = tmp_path / "key.pem"
    await asyncio.to_thread(
        subprocess.run,
        [
            "openssl", "req", "-x509", "-newkey", "rsa:2048", "-nodes",
            "-keyout", str(key), "-out", str(cert), "-days", "1",
            "-subj", "/CN=localhost",
        ],
        check=True,
        capture_output=True,
    )

    from dynamo_tpu.frontend.main import run_frontend
    from dynamo_tpu.runtime import DistributedRuntime
    from dynamo_tpu.runtime.store import StoreServer

    store = StoreServer()
    await store.start()
    rt = await DistributedRuntime.create(store.address)
    ready = asyncio.Event()
    services: list = []
    task = asyncio.create_task(
        run_frontend(
            rt, http_host="127.0.0.1", http_port=0, router_mode="round_robin",
            ready_event=ready, service_out=services,
            tls_cert=str(cert), tls_key=str(key),
        )
    )
    try:
        await asyncio.wait_for(ready.wait(), 10)
        url = f"https://127.0.0.1:{services[0].port}/health"
        ctx = ssl.create_default_context(cafile=str(cert))
        ctx.check_hostname = False
        async with aiohttp.ClientSession() as s:
            async with s.get(url, ssl=ctx) as r:
                assert r.status == 200
            # Plain HTTP against the TLS port must fail.
            with pytest.raises(aiohttp.ClientError):
                async with s.get(
                    f"http://127.0.0.1:{services[0].port}/health"
                ) as r2:
                    await r2.text()
    finally:
        rt.signal_shutdown()
        task.cancel()
        try:
            await rt.shutdown()
        # dynalint: allow-broad-except(best-effort teardown; runtime may already be closed)
        except Exception:
            pass
        await store.stop()


async def test_logprobs_over_http():
    """Logprobs must survive the full data plane (msgpack framing rejects
    int map keys — the engine's logprob records must stay wire-safe)."""
    async with JaxCluster() as c:
        async with aiohttp.ClientSession() as s:
            async with s.post(
                f"{c.base_url}/v1/chat/completions",
                json={
                    "model": "tinyjax",
                    "messages": [{"role": "user", "content": "logprob please"}],
                    "max_tokens": 4,
                    "temperature": 0.0,
                    "logprobs": True,
                    "top_logprobs": 3,
                },
            ) as r:
                assert r.status == 200, await r.text()
                out = await r.json()
            content = out["choices"][0]["logprobs"]["content"]
            assert len(content) == 4
            for e in content:
                assert len(e["top_logprobs"]) == 3
                assert e["logprob"] == e["top_logprobs"][0]["logprob"]

            async with s.post(
                f"{c.base_url}/v1/completions",
                json={
                    "model": "tinyjax",
                    "prompt": "abcd",
                    "max_tokens": 4,
                    "temperature": 0.0,
                    "logprobs": 2,
                },
            ) as r:
                assert r.status == 200, await r.text()
                out = await r.json()
            lp = out["choices"][0]["logprobs"]
            assert len(lp["tokens"]) == 4
            assert len(lp["top_logprobs"][0]) == 2


async def test_clear_kv_blocks_endpoint():
    """Admin endpoint drops cached blocks fleet-wide: a repeated prompt
    that WOULD have hit the prefix cache re-prefills from scratch
    (reference http/service/clear_kv_blocks.rs)."""
    async with JaxCluster() as c:
        async with aiohttp.ClientSession() as s:
            prompt = "cache me if you can " * 4
            body = {
                "model": "tinyjax",
                "messages": [{"role": "user", "content": prompt}],
                "max_tokens": 4,
                "temperature": 0.0,
            }
            async with s.post(f"{c.base_url}/v1/chat/completions", json=body) as r:
                assert r.status == 200

            async with s.post(f"{c.base_url}/clear_kv_blocks") as r:
                assert r.status == 200
                out = await r.json()
            workers = out["cleared"]["tinyjax"]
            assert workers and all(n >= 0 for n in workers.values())
            assert sum(workers.values()) > 0, "nothing was cached/cleared"

            async with s.post(f"{c.base_url}/v1/chat/completions", json=body) as r:
                redo = await r.json()
            cached = (
                redo["usage"].get("prompt_tokens_details") or {}
            ).get("cached_tokens", 0)
            assert cached == 0, "cache survived clear_kv_blocks"
