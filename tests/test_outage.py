"""Control-plane outage tolerance (ISSUE 15): degraded-mode serving
through store blackouts.

The store is a liveness HINT, not a liveness AUTHORITY: session
resurrection replays leases/KV/watches after a store restart, the
keepalive loop survives transient failures, discovery consumers keep a
last-known-good instance snapshot with data-plane-judged quarantine for
lease-expiry deletes, the planner holds actuation on blind windows, and
the fleet harness proves a 60 s blackout is invisible to clients with
degraded mode on — and demonstrably sheds with it off.
"""

import asyncio
import time

import pytest

from dynamo_tpu.runtime.store import StoreClient, StoreServer

pytestmark = [pytest.mark.integration, pytest.mark.pre_merge]


# -- store client session resurrection ---------------------------------------


async def test_keepalive_survives_transient_store_error():
    """The pre-ISSUE-15 bug: the first StoreError killed the keepalive
    loop silently and the lease expired a TTL later. Now a server-side
    lease loss re-attaches the lease under the same id and re-puts its
    keys, from inside the keepalive loop itself."""
    async with StoreServer() as server:
        async with await StoreClient.open(server.address) as c:
            lease = await c.lease_grant(ttl=0.9)
            await c.kv_put("/reg/w1", b"payload", lease=lease)
            # Simulate server-side expiry while the session stays up.
            server._revoke_lease(lease)
            assert await c.kv_get("/reg/w1") is None
            # Within ~2 keepalive beats the loop must notice the
            # StoreError, re-grant, and replay the lease-bound key.
            for _ in range(100):
                if await c.kv_get("/reg/w1") == b"payload":
                    break
                await asyncio.sleep(0.05)
            assert await c.kv_get("/reg/w1") == b"payload"
            assert c.keepalive_failures_total >= 1
            # And the replayed lease is a real lease: revoke deletes.
            await c.lease_revoke(lease)
            assert await c.kv_get("/reg/w1") is None


async def test_ephemeral_lease_not_replayed_after_restart():
    """keepalive=False leases are one-shot (reply keys): replaying them
    after a store restart would resurrect keys consumers already burned.
    Kept-alive leases replay; ephemeral ones must not."""
    server = StoreServer()
    await server.start()
    port = server.port
    client = await StoreClient.open(server.address)
    try:
        durable = await client.lease_grant(ttl=30.0)
        await client.kv_put("/reg/durable", b"d", lease=durable)
        ephemeral = await client.lease_grant(ttl=30.0, keepalive=False)
        await client.kv_put("/oneshot/reply", b"e", lease=ephemeral)
        await server.stop()
        await asyncio.sleep(0.2)
        server2 = StoreServer(port=port)
        await server2.start()
        try:
            for _ in range(100):
                if await _quiet_get(client, "/reg/durable") == b"d":
                    break
                await asyncio.sleep(0.1)
            assert await client.kv_get("/reg/durable") == b"d"
            assert await client.kv_get("/oneshot/reply") is None
            assert client.reconnects_total == 1
        finally:
            await server2.stop()
    finally:
        await client.close()


async def _quiet_get(client, key):
    try:
        return await client.kv_get(key)
    except ConnectionError:
        return None


async def test_subscription_resumes_without_duplicate_events():
    """A resumed pub/sub subscription delivers each post-restart publish
    exactly once — the replay must not double-deliver or inject phantom
    initial events into a plain subject subscription."""
    server = StoreServer()
    await server.start()
    port = server.port
    client = await StoreClient.open(server.address)
    try:
        sub = await client.subscribe("events")
        await server.stop()
        await asyncio.sleep(0.2)
        server2 = StoreServer(port=port)
        await server2.start()
        try:
            for _ in range(100):
                if client.connected and await _quiet_ping(client):
                    break
                await asyncio.sleep(0.1)
            pub = await StoreClient.open(server2.address)
            try:
                await pub.publish("events", b"once")
                msg = await sub.get(timeout=5)
                assert msg["p"] == b"once"
                with pytest.raises(asyncio.TimeoutError):
                    await sub.get(timeout=0.3)
            finally:
                await pub.close()
        finally:
            await server2.stop()
    finally:
        await client.close()


async def _quiet_ping(client) -> bool:
    try:
        return await client.ping() == "pong"
    except ConnectionError:
        return False


async def test_store_client_outage_stats():
    """connected / outage_seconds / reconnects surface the session state
    for the /metrics + /health exports."""
    server = StoreServer()
    await server.start()
    port = server.port
    client = await StoreClient.open(server.address)
    try:
        assert client.connected
        assert client.stats()["connected"] is True
        await server.stop()
        for _ in range(100):
            if not client.connected:
                break
            await asyncio.sleep(0.05)
        assert not client.connected
        await asyncio.sleep(0.15)
        st = client.stats()
        assert st["connected"] is False
        assert st["disconnected_for_s"] > 0.0
        server2 = StoreServer(port=port)
        await server2.start()
        try:
            for _ in range(100):
                if client.connected and await _quiet_ping(client):
                    break
                await asyncio.sleep(0.1)
            st = client.stats()
            assert st["connected"] is True
            assert st["reconnects"] == 1
            assert st["outage_seconds"] > 0.0
            assert st["disconnected_for_s"] == 0.0
        finally:
            await server2.stop()
    finally:
        await client.close()


# -- chaos: the sustained blackout plan --------------------------------------


async def test_store_outage_plan_severs_within_window_only():
    from dynamo_tpu.runtime import chaos

    plan = chaos.ChaosPlan.store_outage(duration_s=60.0)
    now = [1000.0]
    plan.clock = lambda: now[0]
    with pytest.raises(ConnectionError):
        await plan.fire("store.frame", "127.0.0.1:1")
    now[0] += 30.0
    with pytest.raises(ConnectionError):
        await plan.fire("store.connect", "127.0.0.1:1")
    now[0] += 91.0  # past both windows (each clocks from its first hit)
    assert await plan.fire("store.frame", "127.0.0.1:1") is True
    assert await plan.fire("store.connect", "127.0.0.1:1") is True
    assert ("store.frame", "sever", "127.0.0.1:1") in plan.fired


async def test_store_outage_plan_blacks_out_live_session_then_recovers():
    """End to end through a real client: the armed plan severs the live
    session (next inbound frame) and keeps every redial failing until
    the window passes; then the session replays and lease-bound state
    survives."""
    from dynamo_tpu.runtime import chaos

    async with StoreServer() as server:
        client = await StoreClient.open(server.address)
        try:
            lease = await client.lease_grant(ttl=30.0)
            await client.kv_put("/reg/w", b"v", lease=lease)
            plan = chaos.ChaosPlan.store_outage(duration_s=0.8)
            chaos.install(plan)
            try:
                # Any request's response frame trips the sever.
                with pytest.raises(ConnectionError):
                    await client.ping()
                for _ in range(100):
                    if not client.connected:
                        break
                    await asyncio.sleep(0.02)
                assert not client.connected
                # Recovery: once the window passes, redials succeed and
                # the session replays under the same lease id.
                for _ in range(200):
                    if client.connected and await _quiet_ping(client):
                        break
                    await asyncio.sleep(0.05)
                assert await client.kv_get("/reg/w") == b"v"
                assert client.reconnects_total >= 1
            finally:
                chaos.uninstall()
        finally:
            await client.close()


# -- degraded-mode discovery consumers ---------------------------------------


async def test_endpoint_client_quarantines_lease_expiry_when_dataplane_alive():
    """A worker that loses only its STORE session must stay routable:
    the lease-expiry delete is quarantined while the worker's ingress
    answers a probe, and applied only once the data plane goes dark."""
    from dynamo_tpu.runtime import DistributedRuntime

    async with StoreServer() as server:
        worker = await DistributedRuntime.create(server.address)
        frontend = await DistributedRuntime.create(server.address)
        try:
            ep_w = worker.namespace("ns").component("be").endpoint("gen")

            async def handler(req, ctx):
                yield {"ok": True}

            inst = await ep_w.serve(handler)
            ep_f = frontend.namespace("ns").component("be").endpoint("gen")
            client = await ep_f.client()
            client.stale_grace_s = 0.6
            await client.wait_for_instances(1, timeout=5)

            # Sever ONLY the worker's control-plane session (no
            # reconnect): conn-death revokes its lease → delete(lease).
            worker.store.auto_reconnect = False
            await worker.store.close()
            for _ in range(100):
                if client.quarantined_total >= 1:
                    break
                await asyncio.sleep(0.05)
            assert client.quarantined_total == 1
            # Still cached, still routable — the degraded-mode contract.
            assert inst.instance_id in client.instances
            stream = await client.direct(inst.instance_id, {"q": 1})
            got = [item async for item in stream]
            assert got == [{"ok": True}]

            # Now the data plane dies too: the deferred delete applies
            # within one grace sweep.
            await worker.ingress.stop()
            for _ in range(100):
                if inst.instance_id not in client.instances:
                    break
                await asyncio.sleep(0.1)
            assert inst.instance_id not in client.instances
            assert client.quarantine_expired_total == 1
            await client.stop()
        finally:
            await frontend.shutdown()
            await worker.shutdown()


async def test_endpoint_client_honors_explicit_deregister():
    """Graceful drain retractions (explicit kv_del) are never
    quarantined, even with the data plane alive and grace on."""
    from dynamo_tpu.runtime import DistributedRuntime

    async with StoreServer() as server:
        worker = await DistributedRuntime.create(server.address)
        frontend = await DistributedRuntime.create(server.address)
        try:
            ep_w = worker.namespace("ns").component("be").endpoint("gen")

            async def handler(req, ctx):
                yield {}

            inst = await ep_w.serve(handler)
            ep_f = frontend.namespace("ns").component("be").endpoint("gen")
            client = await ep_f.client()
            client.stale_grace_s = 60.0
            await client.wait_for_instances(1, timeout=5)
            await ep_w.deregister(inst.instance_id)
            for _ in range(100):
                if inst.instance_id not in client.instances:
                    break
                await asyncio.sleep(0.05)
            assert inst.instance_id not in client.instances
            assert client.quarantined_total == 0
            await client.stop()
        finally:
            await frontend.shutdown()
            await worker.shutdown()


async def test_model_watcher_defers_lease_removal_and_cancels_on_reregister():
    """A last-instance lease expiry with a live data plane defers the
    model removal; re-registration within grace cancels it — zero flap
    reaches the ModelManager."""
    from dynamo_tpu.llm.discovery import ModelWatcher, register_llm
    from dynamo_tpu.llm.model_card import ModelDeploymentCard
    from dynamo_tpu.runtime import DistributedRuntime

    async with StoreServer() as server:
        front = await DistributedRuntime.create(server.address)
        worker = await DistributedRuntime.create(server.address)
        removed: list = []
        watcher = ModelWatcher(
            front.store, stale_grace_s=1.0, data_plane_live=lambda name: True
        )

        async def on_rm(name):
            removed.append(name)

        watcher.on_model_removed.append(on_rm)
        await watcher.start()
        try:
            ep = worker.namespace("ns").component("be").endpoint("gen")

            async def handler(req, ctx):
                yield {}

            await ep.serve(handler)
            await register_llm(ep, ModelDeploymentCard(name="tiny", context_length=128))
            for _ in range(100):
                if watcher._counts.get("tiny"):
                    break
                await asyncio.sleep(0.02)

            # Lease loss (store session severed), data plane "alive".
            worker.store.auto_reconnect = False
            await worker.store.close()
            for _ in range(100):
                if watcher.deferred_removals_total:
                    break
                await asyncio.sleep(0.02)
            assert watcher.deferred_removals_total == 1
            assert removed == []

            # Re-register within grace from a fresh runtime: the pending
            # removal cancels — the model never flapped.
            worker2 = await DistributedRuntime.create(server.address)
            try:
                ep2 = worker2.namespace("ns").component("be").endpoint("gen")
                await ep2.serve(handler)
                await register_llm(
                    ep2, ModelDeploymentCard(name="tiny", context_length=128)
                )
                for _ in range(100):
                    if watcher.flaps_avoided_total:
                        break
                    await asyncio.sleep(0.02)
                assert watcher.flaps_avoided_total == 1
                await asyncio.sleep(1.2)  # past the original grace
                assert removed == []
            finally:
                await worker2.shutdown()
        finally:
            await watcher.stop()
            await front.shutdown()
            await worker.shutdown()


async def test_model_watcher_duplicate_delete_underflow_guard():
    """A duplicate/late delete must not underflow the instance count
    (which would make the next 0→1 transition invisible forever)."""
    from dynamo_tpu.llm.discovery import ModelEntry, ModelWatcher

    watcher = ModelWatcher(store=None, stale_grace_s=0.0)
    entry = ModelEntry(
        name="m", namespace="ns", component="be", endpoint="gen",
        instance_id=1, mdc_checksum="x",
    )
    watcher._instances["/dynamo/models/m/1"] = entry
    watcher._instances["/dynamo/models/m/2"] = entry
    watcher._counts["m"] = 1  # desynced: two keys, count 1
    fired: list = []

    async def on_rm(name):
        fired.append(name)

    watcher.on_model_removed.append(on_rm)

    ev1 = StoreClient.as_watch_event(
        {"t": "delete", "k": "/dynamo/models/m/1", "v": b"", "rev": 1}
    )
    ev2 = StoreClient.as_watch_event(
        {"t": "delete", "k": "/dynamo/models/m/2", "v": b"", "rev": 2}
    )
    await watcher._on_delete(ev1)
    assert fired == ["m"]
    await watcher._on_delete(ev2)  # would underflow pre-fix
    assert fired == ["m"]
    assert watcher._counts.get("m", 0) == 0


async def test_model_watcher_stop_awaits_and_is_idempotent():
    async with StoreServer() as server:
        from dynamo_tpu.llm.discovery import ModelWatcher

        client = await StoreClient.open(server.address)
        try:
            watcher = ModelWatcher(client, stale_grace_s=0.0)
            await watcher.start()
            task = watcher._task
            await watcher.stop()
            assert task.done()
            assert watcher._task is None
            await watcher.stop()  # second stop is a no-op, not an error
        finally:
            await client.close()


# -- planner + obs degraded behavior -----------------------------------------


def test_controller_holds_on_degraded_observation():
    from dynamo_tpu.planner.controller import ControllerConfig, PlannerController
    from dynamo_tpu.planner.planner_core import (
        Observation,
        Planner,
        PlannerConfig,
        SlaTargets,
    )
    from dynamo_tpu.planner.perf_interpolation import from_profile
    from dynamo_tpu.fleet.harness import mocker_profile

    class Connector:
        def __init__(self):
            self.calls = []

        async def set_replicas(self, component, replicas):
            self.calls.append((component, replicas))

        def current(self, component):
            return 1

    prefill_i, decode_i = from_profile(mocker_profile(20_000.0, 100.0, 5_000.0, 4))
    conn = Connector()
    planner = Planner(
        prefill_i, decode_i, conn,
        sla=SlaTargets(ttft_s=0.35, itl_s=0.08),
        config=PlannerConfig(min_replicas=1, max_replicas=8),
    )
    t = [0.0]
    ctl = PlannerController(
        planner, conn, pools={"backend": "max"},
        config=ControllerConfig(min_replicas=1, max_replicas=8),
        clock=lambda: t[0],
    )

    async def run():
        t[0] = 100.0
        dark = Observation(
            request_rate=0.0, mean_isl=64.0, mean_osl=8.0,
            control_plane_degraded=True,
        )
        actions = await ctl.cycle(dark)
        assert set(actions.values()) == {"degraded_hold"}
        assert conn.calls == []  # no actuation on a blind window
        # Hysteresis must not have advanced: a healthy cycle afterwards
        # decides from real signal.
        assert ctl.pools["backend"].below_streak == 0
        t[0] = 200.0
        live = Observation(request_rate=30.0, mean_isl=64.0, mean_osl=8.0)
        actions = await ctl.cycle(live)
        assert actions["backend"] in ("scale_up", "hold")
        assert conn.calls  # actuation resumed

    asyncio.run(run())
    assert ctl.decisions["degraded_hold"] == 1


def test_fleet_aggregator_dark_is_not_dead():
    """While the store session is down, snapshot silence retires NOTHING
    (publisher dead vs control plane dark); after reconnection every
    publisher gets one fresh stale window before retirement resumes."""
    from dynamo_tpu.obs.aggregator import FleetAggregator
    from dynamo_tpu.obs.snapshot import MetricSnapshot

    class FakeStore:
        connected = True

    store = FakeStore()
    agg = FleetAggregator(store, stale_after_s=1.0)
    snap = MetricSnapshot(worker_id=7, role="worker", component="backend")
    agg.ingest(snap)
    snap.received_at = time.time() - 100.0  # long silent
    store.connected = False
    assert agg.control_plane_dark
    assert agg.sweep_stale() == []           # dark: not dead
    assert 7 in agg.latest
    store.connected = True
    assert agg.sweep_stale() == []           # re-publish grace window
    assert agg.sweep_stale(now=time.time() + 2.0) == [7]  # grace over
    assert 7 not in agg.latest


def test_worker_monitor_degraded_tracks_store_connectivity():
    """The busy-set view freezes at last-known-good while the control
    plane is dark; ``degraded`` is the consumer-facing flag for it."""
    from dynamo_tpu.llm.kv_router.publisher import MetricsAggregator
    from dynamo_tpu.runtime.worker_monitor import WorkerMonitor

    class FakeStore:
        connected = True

    store = FakeStore()
    monitor = WorkerMonitor(store, "ns", "be")
    assert monitor.degraded is False
    store.connected = False
    assert monitor.degraded is True
    assert monitor.aggregator.degraded is True
    # __new__-built partial aggregators (the established test pattern)
    # must not blow up on the property.
    partial = MetricsAggregator.__new__(MetricsAggregator)
    assert partial.degraded is False


def test_fleet_aggregator_observation_flags_degraded():
    from dynamo_tpu.obs.aggregator import FleetAggregator

    class FakeStore:
        connected = False

    agg = FleetAggregator(FakeStore(), stale_after_s=1.0)
    obs = agg.observation()
    assert obs.control_plane_degraded is True


# -- surfaces ----------------------------------------------------------------


async def test_store_gauges_and_health_on_status_server():
    from dynamo_tpu.runtime.status_server import SystemStatusServer, bind_store_gauges

    async with StoreServer() as server:
        client = await StoreClient.open(server.address)
        try:
            status = SystemStatusServer()
            bind_store_gauges(status, client)
            for hook in status.before_render:
                hook()
            text = status.metrics.render().decode()
            for name in (
                "dynamo_store_connected",
                "dynamo_store_outage_seconds",
                "dynamo_store_keepalive_failures_total",
                "dynamo_store_session_rebuilds_total",
            ):
                assert name in text, name
            assert 'dynamo_store_connected{service="store"} 1.0' in text
            assert status.store is client
        finally:
            await client.close()


# -- the fleet-harness blackout scenario (the acceptance criterion) ----------


def test_fleet_blackout_degraded_serves_strict_sheds():
    """60 s store blackout mid-diurnal-run (ISSUE 15 acceptance):

    degraded mode — every stream bit-identical to the no-fault run, new
    requests during the blackout route on cached instances, zero model
    flaps, the controller holds (degraded_hold), and on recovery every
    worker re-registers within one lease TTL with inventories resynced;

    strict mode (grace = 0) — the SAME scenario demonstrably sheds once
    leases expire, pinning that the degraded path is load-bearing."""
    from dynamo_tpu.fleet.harness import run_blackout_ab

    r = run_blackout_ab(
        duration_s=240.0, blackout_at=90.0, blackout_s=60.0,
        seed=3, lease_ttl_s=10.0, stale_grace_s=120.0,
    )
    no_fault, degraded, strict = r["no_fault"], r["degraded"], r["strict"]

    # Degraded mode: the blackout is invisible to clients.
    assert degraded.broken_streams == 0
    assert degraded.streams == no_fault.streams  # bit-identical fleet-wide
    assert degraded.blackout_routed >= 1
    assert degraded.blackout_shed == 0
    assert degraded.model_flaps == 0
    assert degraded.decisions.get("degraded_hold", 0) >= 1
    # Recovery: every blacked-out worker re-registered within one lease
    # TTL and resynced its KV inventory on session replay.
    assert degraded.kv_resyncs >= 1
    assert 0.0 < degraded.reregister_lag_s <= 10.0

    # Strict mode (grace = 0): lease expiry collapses routing — the same
    # scenario sheds new requests and flaps the model add/remove.
    assert strict.blackout_shed >= 1
    assert strict.model_flaps >= 2  # removed at expiry, re-added on recovery
    assert strict.shed >= strict.blackout_shed
