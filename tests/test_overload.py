"""Overload robustness (ISSUE 10): admission control, per-tenant fair
queueing, deadline shedding, and disconnect-while-queued cleanup.

The contract under test, end to end: a saturated deployment DEGRADES —
it never breaks. Admitted streams complete bit-identically to an
unloaded run; everything else exits through a typed, retryable error
(429/503 + Retry-After on HTTP, shed/deadline wire markers on the data
plane); a flooding tenant cannot starve a light one (DRR fair queues);
and nothing queued leaks blocks or router pins when it is cancelled,
shed, or expired.
"""

import asyncio
import time
from contextlib import suppress

import pytest

from dynamo_tpu.engine.fair_queue import FairQueue
from dynamo_tpu.llm.admission import (
    AdmissionConfig,
    AdmissionController,
    resolve_deadline,
)
from dynamo_tpu.llm.mocker import MockEngineArgs, MockTpuEngine
from dynamo_tpu.llm.protocols.common import (
    PreprocessedRequest,
    SamplingOptions,
    StopConditions,
)
from dynamo_tpu.runtime import chaos
from dynamo_tpu.runtime.engine import (
    Context,
    DeadlineExceededError,
    EngineOverloadedError,
)

pytestmark = [pytest.mark.unit, pytest.mark.pre_merge]


class Item:
    def __init__(self, name, tenant="", cost=1, priority=0):
        self.name = name
        self.tenant_id = tenant
        self.cost = cost
        self.priority = priority

    def __repr__(self):
        return f"Item({self.name})"


def fq(**kw):
    kw.setdefault("quantum", 8)
    kw.setdefault("cost_fn", lambda it: it.cost)
    return FairQueue(**kw)


# -- FairQueue unit surface ---------------------------------------------------


def test_fair_queue_single_tenant_is_fifo():
    """One tenant (or fairness off): pop order IS arrival order — the
    structural half of the bit-identity invariant."""
    for fair in (True, False):
        q = fq(fair=fair)
        items = [Item(f"i{i}", tenant="t", cost=3 + i) for i in range(10)]
        for it in items:
            q.append(it)
        assert [q.pop() for _ in range(10)] == items
        assert len(q) == 0 and not q


def test_fair_queue_drr_interleaves_heavy_and_light():
    """A heavy tenant's backlog cannot monopolize admission: with equal
    quanta, pops alternate between tenants even when the heavy tenant
    arrived first with 10x the requests."""
    q = fq(quantum=4)
    heavy = [Item(f"h{i}", tenant="heavy", cost=4) for i in range(10)]
    light = [Item(f"l{i}", tenant="light", cost=4) for i in range(2)]
    for it in heavy:
        q.append(it)
    for it in light:
        q.append(it)
    order = [q.pop().name for _ in range(6)]
    # Both light requests admit within the first two rounds, not after
    # the entire heavy backlog.
    assert "l0" in order[:2] or "l0" in order[:3]
    assert "l1" in order[:5]
    assert set(order) != {f"h{i}" for i in range(6)}


def test_fair_queue_token_cost_weighs_admission():
    """DRR is over TOKEN cost, not request count: a tenant of huge
    prompts earns the same token bandwidth as a tenant of small ones —
    so the small-prompt tenant admits ~cost_ratio more requests."""
    q = fq(quantum=8)
    for i in range(8):
        q.append(Item(f"big{i}", tenant="big", cost=16))
    for i in range(8):
        q.append(Item(f"small{i}", tenant="small", cost=2))
    first8 = [q.pop().name for _ in range(8)]
    n_small = sum(1 for n in first8 if n.startswith("small"))
    n_big = 8 - n_small
    assert n_small > n_big  # more small admissions per token of share


def test_fair_queue_priority_orders_within_tenant_only():
    q = fq()
    q.append(Item("a", tenant="t1", priority=0))
    q.append(Item("b", tenant="t1", priority=5))
    q.append(Item("c", tenant="t1", priority=5))
    assert [q.pop().name for _ in range(3)] == ["b", "c", "a"]
    # Fairness OFF: everyone shares one queue, so a client-controlled
    # priority must NOT jump it (that would be cross-tenant queue
    # jumping, and would break the off == exact-FIFO invariant).
    q = fq(fair=False)
    q.append(Item("a", tenant="t1", priority=0))
    q.append(Item("b", tenant="t2", priority=100))
    assert [q.pop().name for _ in range(2)] == ["a", "b"]


def test_fair_queue_sweep_and_remove_any_position():
    q = fq()
    items = [Item(f"i{i}", tenant=f"t{i % 2}") for i in range(6)]
    for it in items:
        q.append(it)
    removed = q.sweep(lambda it: it.name in ("i2", "i3", "i5"))
    assert {it.name for it in removed} == {"i2", "i3", "i5"}
    assert len(q) == 3 and items[2] not in q
    assert q.remove(items[0]) and not q.remove(items[0])
    # Draining a tenant entirely drops it from rotation + stats.
    q.sweep(lambda it: True)
    assert len(q) == 0 and q.stats() == {}


def test_fair_queue_appendleft_requeues_first():
    q = fq()
    a, b, c = Item("a", "t1"), Item("b", "t2"), Item("c", "t1")
    for it in (a, b, c):
        q.append(it)
    victim = q.pop()
    q.appendleft(victim)  # preemption requeue: next admission candidate
    assert q.pop() is victim


def test_fair_queue_stats_snapshot():
    q = fq()
    q.append(Item("a", tenant="gold", cost=5))
    q.append(Item("b", tenant="", cost=2))
    st = q.stats()
    assert st["gold"]["depth"] == 1.0
    assert st["default"]["depth"] == 1.0


# -- frontend admission unit surface -----------------------------------------


def test_token_bucket_rate_limit_and_retry_after():
    clock = [0.0]
    ctl = AdmissionController(
        AdmissionConfig(tenant_rate=2.0, tenant_burst=2), clock=lambda: clock[0]
    )
    assert ctl.admit("a").admitted and ctl.admit("a").admitted
    d = ctl.admit("a")
    assert not d.admitted and d.status == 429 and d.reason == "rate_limit"
    assert 0 < d.retry_after_s <= 0.5 + 1e-6  # 2 req/s -> half-second refill
    # Another tenant has its own bucket.
    assert ctl.admit("b").admitted
    # Refill admits again.
    clock[0] += 0.6
    assert ctl.admit("a").admitted
    assert ctl.shed_total == 1


def test_inflight_ceiling_sheds_503():
    ctl = AdmissionController(AdmissionConfig(max_inflight=2))
    assert ctl.admit("x").admitted and ctl.admit("y").admitted
    d = ctl.admit("z")
    assert not d.admitted and d.status == 503 and d.reason == "queue_full"
    ctl.release()
    assert ctl.admit("z").admitted


def test_ceiling_rejection_refunds_rate_token():
    """A 503 at the ceiling must not also burn the tenant's rate token —
    the advertised retry would then 429 for capacity never used."""
    clock = [0.0]
    ctl = AdmissionController(
        AdmissionConfig(tenant_rate=1.0, tenant_burst=1, max_inflight=1),
        clock=lambda: clock[0],
    )
    assert ctl.admit("a").admitted  # fills the ceiling, spends a's token
    d = ctl.admit("b")              # fresh bucket, ceiling-shed
    assert not d.admitted and d.reason == "queue_full"
    ctl.release()
    # b's token was refunded: it admits immediately, no 429 detour.
    assert ctl.admit("b").admitted


def test_resolve_deadline_header_wins_and_validates():
    ms, epoch, err = resolve_deadline(500.0, None, now_epoch=100.0)
    assert (ms, epoch, err) == (500.0, 100.5, None)
    ms, epoch, err = resolve_deadline(500.0, "250", now_epoch=100.0)
    assert (ms, epoch) == (250.0, 100.25) and err is None
    assert resolve_deadline(None, None)[0] is None
    assert resolve_deadline(None, "nope")[2] is not None
    assert resolve_deadline(-5.0, None)[2] is not None


def test_worker_monitor_marks_saturated_queues_busy():
    from dynamo_tpu.llm.kv_router.protocols import (
        ForwardPassMetrics,
        KvStats,
        WorkerStats,
    )
    from dynamo_tpu.runtime.worker_monitor import WorkerMonitor

    mon = WorkerMonitor.__new__(WorkerMonitor)
    mon.busy_threshold = 0.95
    mon.queue_threshold = None  # auto: the worker-exported queue limit
    mon.busy = set()
    mon.on_busy_change = lambda w, b: None
    sat = ForwardPassMetrics(
        worker_id=1,
        worker=WorkerStats(num_requests_waiting=4, queue_limit=4),
        kv=KvStats(gpu_cache_usage_perc=0.1),
    )
    idle = ForwardPassMetrics(
        worker_id=2,
        worker=WorkerStats(num_requests_waiting=1, queue_limit=4),
        kv=KvStats(gpu_cache_usage_perc=0.1),
    )
    mon._on_metrics(sat)
    mon._on_metrics(idle)
    assert mon.busy == {1}
    assert mon.eligible([1, 2]) == [2]
    # Explicit threshold overrides the exported limit.
    mon.queue_threshold = 1
    mon._on_metrics(idle)
    assert mon.busy == {1, 2}
    assert mon.eligible([1, 2]) == [1, 2]  # all busy -> full set fallback


def test_fair_queue_gauges_bounded_and_removed():
    """Tenant labels are client-controlled: the export caps distinct
    series (overflow under __other__) and REMOVES drained tenants'
    series — a rotating x-tenant-id spray cannot grow /metrics forever."""
    from dynamo_tpu.runtime.status_server import (
        MAX_TENANT_GAUGES,
        SystemStatusServer,
        bind_fair_queue_gauges,
    )

    stats: dict = {}
    status = SystemStatusServer()
    bind_fair_queue_gauges(status, lambda: stats)

    def render() -> str:
        for hook in status.before_render:
            hook()
        return status.metrics.render().decode()

    stats = {
        f"t{i}": {"depth": float(i), "deficit": 0.0}
        for i in range(MAX_TENANT_GAUGES + 20)
    }
    text = render()
    assert 'tenant="__other__"' in text
    assert text.count("scheduler_tenant_queue_depth{") == MAX_TENANT_GAUGES + 1
    # Everything drains: every tenant series disappears from the output.
    stats = {}
    text = render()
    assert "scheduler_tenant_queue_depth{" not in text


def test_chaos_burst_plan_validates():
    plan = chaos.ChaosPlan.burst(slow_s=0.01, shed_p=0.25, seed=7)
    points = {r.point for r in plan.rules}
    assert points == {"engine.step", "frontend.admit"}
    with pytest.raises(ValueError, match="unknown chaos point"):
        chaos.ChaosRule(point="frontend.nope", action="drop")


# -- engine-level behavior (real EngineCore, tiny model) ----------------------


def _core(**over):
    from dynamo_tpu.engine import EngineCore, tiny_engine, tiny_model

    return EngineCore(tiny_model(), tiny_engine(**over), seed=0)


def _req(prompt, rid, max_tokens=8, temperature=0.0, seed=None, **kw):
    return PreprocessedRequest(
        model="tiny",
        token_ids=prompt,
        request_id=rid,
        sampling=SamplingOptions(temperature=temperature, seed=seed),
        stop=StopConditions(max_tokens=max_tokens),
        **kw,
    )


def _run_all(core, seqs, max_steps=2000):
    done = {s.request_id: [] for s in seqs}
    finishes = {}
    for _ in range(max_steps):
        for seq, out in core.step():
            done[seq.request_id].extend(out.token_ids)
            if out.finish_reason:
                finishes[seq.request_id] = out.finish_reason
        if len(finishes) == len(seqs):
            break
    return done, finishes


def test_single_tenant_bit_identity_fair_on_vs_off():
    """Acceptance: single-tenant, under-limit traffic is bit-identical
    with the fairness scheduler on vs off — greedy AND seeded
    temperature, waves AND chunked."""
    import numpy as np

    rng = np.random.RandomState(3)
    prompts = [list(rng.randint(1, 200, size=12 + 7 * i)) for i in range(5)]

    def run(fair, scheduling):
        core = _core(fair_scheduling=fair, scheduling=scheduling)
        seqs = []
        for i, p in enumerate(prompts):
            temp = 0.0 if i % 2 == 0 else 0.8
            seqs.append(
                core.add_request(
                    _req(p, f"r{i}", max_tokens=6, temperature=temp, seed=11 + i)
                )
            )
        return _run_all(core, seqs)

    for scheduling in ("waves", "chunked"):
        off = run(False, scheduling)
        on = run(True, scheduling)
        assert on == off, f"fairness changed tokens under {scheduling}"


def test_engine_deadline_expiry_typed_and_leak_free():
    """A request whose deadline passes while QUEUED gets the typed error
    frame; blocks and pins stay untouched (it was never admitted)."""
    core = _core(max_num_seqs=1)
    a = core.add_request(_req([1] * 16, "running", max_tokens=20))
    # Fill the single slot so the second request stays queued.
    core.step()
    assert a in core.running
    expired = core.add_request(
        _req([2] * 16, "expired", deadline_epoch=time.time() - 1.0)
    )
    outs = []
    for _ in range(5):
        outs.extend(core.step())
        if any(s.request_id == "expired" for s, _ in outs):
            break
    shed = [(s, o) for s, o in outs if s.request_id == "expired"]
    assert len(shed) == 1
    s, o = shed[0]
    assert o.finish_reason == "error" and o.meta["shed"] == "deadline"
    assert "expired" in o.meta["detail"]
    assert core.sched_stats["deadline_expired_total"] == 1
    assert expired not in core.waiting and expired not in core.running
    # Zero leaked blocks: every allocated block belongs to the RUNNING
    # sequence (the expired one held nothing and pinned nothing).
    assert not expired.block_ids and not expired.pinned_hashes
    assert (
        core.allocator.capacity - core.allocator.free_blocks
        == len(a.block_ids)
    )
    # An ADMITTED request past its deadline still completes (no broken
    # streams, ever).
    a.deadline_epoch = time.time() - 1.0
    _done, fin = _run_all(core, [a])
    assert fin["running"] == "length" and a.generated == 20


def test_engine_bounded_queue_sheds_typed():
    core = _core(max_waiting=2, max_num_seqs=1)
    core.add_request(_req([1] * 8, "r0", max_tokens=4))
    core.step()  # admit r0 so the queue is purely waiting depth
    core.add_request(_req([2] * 8, "r1"))
    core.add_request(_req([3] * 8, "r2"))
    with pytest.raises(EngineOverloadedError, match="queue full"):
        core.add_request(_req([4] * 8, "r3"))
    assert core.sched_stats["shed_total"] == 1
    assert core.scheduler_stats()["queue_limit"] == 2
    fpm = core.metrics()
    assert fpm.worker.queue_limit == 2
    assert fpm.worker.requests_shed_total == 1


def test_engine_cancel_while_queued_removes_mid_queue():
    """Satellite: a cancelled request leaves the waiting queue from ANY
    position — even parked behind an unadmittable head — and leaks
    nothing."""
    core = _core(max_num_seqs=1)
    a = core.add_request(_req([1] * 16, "a", max_tokens=30))
    core.step()
    b = core.add_request(_req([2] * 16, "b", max_tokens=4))
    c = core.add_request(_req([3] * 16, "c", max_tokens=4))
    core.step()
    assert b in core.waiting and c in core.waiting
    core.cancel_request(c)  # cancel BEHIND the queue head
    core.step()
    assert c not in core.waiting and b in core.waiting
    # The cancelled request held nothing; everything allocated is a's.
    assert not c.block_ids and not c.pinned_hashes
    assert (
        core.allocator.capacity - core.allocator.free_blocks
        == len(a.block_ids)
    )
    done, fin = _run_all(core, [a, b])
    assert fin == {"a": "length", "b": "length"}


async def test_tpu_engine_surfaces_deadline_as_typed_error():
    from dynamo_tpu.engine import TpuEngine

    core = _core(max_num_seqs=1)
    engine = TpuEngine(core)
    ctx = Context()

    async def consume(gen):
        return [o async for o in gen]

    blocker = asyncio.create_task(
        consume(
            engine.generate(
                _req([1] * 16, "blk", max_tokens=40).to_wire(), Context()
            )
        )
    )
    for _ in range(100):
        await asyncio.sleep(0.01)
        if core.running:
            break
    with pytest.raises(DeadlineExceededError, match="expired"):
        async for _ in engine.generate(
            _req([2] * 16, "late", deadline_epoch=time.time() - 1.0).to_wire(),
            ctx,
        ):
            pass
    await blocker


# -- mocker fairness property (virtual clock) --------------------------------


def _mock_seq(rid, prompt, max_tokens, tenant, deadline=None):
    from dynamo_tpu.llm.mocker.engine import _Seq
    from dynamo_tpu.tokens import TokenBlockSequence, compute_seq_hashes

    s = _Seq(
        request_id=rid,
        prompt=prompt,
        max_tokens=max_tokens,
        out=asyncio.Queue(),
        seq=TokenBlockSequence(prompt, 8),
        prompt_hashes=compute_seq_hashes(prompt, 8),
        stop=StopConditions(max_tokens=max_tokens, ignore_eos=True),
        tenant_id=tenant,
    )
    s.deadline_epoch = deadline
    return s


def _drive_mocker(fair, heavy_n, light_arrivals, max_vt=60.0):
    """Deterministic virtual-clock drive: a heavy tenant floods at t=0
    with short completions (slots turn over fast — admission order, not
    preemption, is what is under test), a light tenant arrives on a
    schedule; returns per-request first-token virtual times.
    (bench.py run_overload_ab is the reported twin.)"""
    args = MockEngineArgs(
        num_kv_blocks=4096, block_size=8, max_num_seqs=2,
        max_num_batched_tokens=128, enable_prefix_caching=False,
        fair_scheduling=fair, fair_quantum=32,
    )
    eng = MockTpuEngine(args)
    heavy = [
        _mock_seq(f"h{i}", [1 + (i % 7)] * 32, 1, "heavy")
        for i in range(heavy_n)
    ]
    light = [
        _mock_seq(f"l{i}", [9] * 32, 4, "light")
        for i in range(len(light_arrivals))
    ]
    pending = sorted(
        zip(light_arrivals, light), key=lambda p: p[0]
    )
    for s in heavy:
        eng._waiting.append(s)
    vt = 0.0
    first: dict[str, float] = {}
    live = list(heavy)
    while vt < max_vt and (pending or any(
        s in eng._waiting or s in eng._running for s in live
    )):
        while pending and pending[0][0] <= vt:
            _, s = pending.pop(0)
            s.t_submit_vt = vt
            eng._waiting.append(s)
            live.append(s)
        eng._admit()
        p, d = eng._step()
        vt += (
            args.base_iter_us
            + p * args.prefill_us_per_token
            + d * args.decode_us_per_seq
        ) / 1e6
        for s in live:
            while not s.out.empty():
                item = s.out.get_nowait()
                if isinstance(item, dict) and item.get("token_ids"):
                    first.setdefault(s.request_id, vt)
    return {
        rid: t - getattr(
            next(s for s in live if s.request_id == rid), "t_submit_vt", 0.0
        )
        for rid, t in first.items()
    }


def test_mocker_fairness_bounds_light_tenant_ttft():
    """Acceptance: under a heavy-tenant flood, fairness on holds the
    light tenant's worst TTFT within 2x its unloaded value; FIFO does
    not. Deterministic mocker virtual clock."""
    arrivals = [0.02 * i for i in range(6)]
    unloaded = _drive_mocker(fair=False, heavy_n=0, light_arrivals=arrivals)
    fifo = _drive_mocker(fair=False, heavy_n=40, light_arrivals=arrivals)
    fair = _drive_mocker(fair=True, heavy_n=40, light_arrivals=arrivals)

    def light_worst(res):
        vals = [t for r, t in res.items() if r.startswith("l")]
        assert len(vals) == len(arrivals), f"light requests lost: {res}"
        return max(vals)

    u, f_on, f_off = light_worst(unloaded), light_worst(fair), light_worst(fifo)
    assert f_on <= 2.0 * u, (
        f"fair scheduling failed the SLO: worst light TTFT {f_on:.3f}s vs "
        f"unloaded {u:.3f}s"
    )
    assert f_off > 2.0 * u, (
        f"FIFO unexpectedly held the SLO ({f_off:.3f}s vs {u:.3f}s) — "
        "the load is not saturating; fix the test setup"
    )
    assert f_on < f_off


def test_mocker_deadline_expiry_on_virtual_clock():
    """Queued-past-deadline requests shed with the typed frame on the
    INJECTED clock; pins/partials fully released."""
    args = MockEngineArgs(
        num_kv_blocks=256, block_size=8, max_num_seqs=1,
        enable_prefix_caching=False,
    )
    eng = MockTpuEngine(args)
    clock = [1000.0]
    eng.clock = lambda: clock[0]
    running = _mock_seq("run", [1] * 16, 8, "")
    late = _mock_seq("late", [2] * 16, 8, "", deadline=1005.0)
    eng._waiting.append(running)
    eng._waiting.append(late)
    eng._admit()
    assert running in eng._running and late in eng._waiting
    clock[0] = 1010.0  # virtual deadline passes while queued
    eng._admit()
    assert late not in eng._waiting
    item = late.out.get_nowait()
    assert item["finish_reason"] == "error"
    assert item["meta"]["shed"] == "deadline"
    assert eng.sched_stats["deadline_expired_total"] == 1
    # Drain the running seq; every block returns.
    for _ in range(50):
        eng._admit()
        eng._step()
        if running not in eng._running:
            break
    assert eng.kv.free_blocks == eng.kv.capacity


async def test_mocker_generate_bounded_queue_and_deadline_raise():
    eng = MockTpuEngine(
        MockEngineArgs(
            num_kv_blocks=256, block_size=4, max_num_seqs=1, max_waiting=1,
            speedup_ratio=1000.0, decode_us_per_seq=50000.0,
        )
    )

    def wire(rid, **kw):
        return PreprocessedRequest(
            model="mock", token_ids=[1] * 12, request_id=rid,
            stop=StopConditions(max_tokens=50), **kw,
        ).to_wire()

    async def consume(gen):
        with suppress(Exception):
            async for _ in gen:
                pass

    t1 = asyncio.create_task(consume(eng.generate(wire("a"), Context())))
    for _ in range(200):
        await asyncio.sleep(0.005)
        if eng._running:
            break
    t2 = asyncio.create_task(consume(eng.generate(wire("b"), Context())))
    for _ in range(200):
        await asyncio.sleep(0.005)
        if len(eng._waiting):
            break
    with pytest.raises(EngineOverloadedError, match="queue full"):
        async for _ in eng.generate(wire("c"), Context()):
            pass
    assert eng.sched_stats["shed_total"] == 1
    t1.cancel()
    t2.cancel()
    for t in (t1, t2):
        with suppress(asyncio.CancelledError):
            await t


async def test_mocker_generate_deadline_expired_raise():
    eng = MockTpuEngine(
        MockEngineArgs(
            num_kv_blocks=256, block_size=4, max_num_seqs=1,
            speedup_ratio=1000.0, decode_us_per_seq=20000.0,
        )
    )

    async def consume(gen):
        with suppress(Exception):
            async for _ in gen:
                pass

    blocker = asyncio.create_task(
        consume(
            eng.generate(
                PreprocessedRequest(
                    model="mock", token_ids=[1] * 12, request_id="blk",
                    stop=StopConditions(max_tokens=100),
                ).to_wire(),
                Context(),
            )
        )
    )
    for _ in range(200):
        await asyncio.sleep(0.005)
        if eng._running:
            break
    with pytest.raises(DeadlineExceededError, match="expired"):
        async for _ in eng.generate(
            PreprocessedRequest(
                model="mock", token_ids=[2] * 12, request_id="late",
                stop=StopConditions(max_tokens=4),
                deadline_epoch=time.time() - 1.0,
            ).to_wire(),
            Context(),
        ):
            pass
    blocker.cancel()
    with suppress(asyncio.CancelledError):
        await blocker


# -- wire + migration behavior ------------------------------------------------


async def test_shed_worker_retries_elsewhere_stream_intact():
    """A full worker's shed is the PR 6 retry-elsewhere shape: migration
    moves the request to the other instance and the client stream is
    bit-identical to a clean run — zero broken streams."""
    from dynamo_tpu.llm.migration import Migration
    from dynamo_tpu.runtime import DistributedRuntime
    from dynamo_tpu.runtime.store import StoreServer

    store = StoreServer()
    await store.start()
    rts, engines = [], []
    try:
        for i, args in enumerate(
            (
                # Worker 0: one slot, slow, queue bounded at 1 -> sheds.
                MockEngineArgs(
                    num_kv_blocks=256, block_size=8, max_num_seqs=1,
                    max_waiting=1, decode_us_per_seq=200000.0,
                ),
                # Worker 1: healthy.
                MockEngineArgs(num_kv_blocks=256, block_size=8),
            )
        ):
            rt = await DistributedRuntime.create(store.address)
            engine = MockTpuEngine(args)
            ep = rt.namespace("ovl").component("w").endpoint("generate")

            async def handler(req, ctx, engine=engine):
                async for out in engine.generate(req, ctx):
                    yield out

            await ep.serve(handler)
            rts.append(rt)
            engines.append(engine)
        client_rt = await DistributedRuntime.create(store.address)
        client = await (
            client_rt.namespace("ovl").component("w").endpoint("generate").client()
        )
        await client.wait_for_instances(2, timeout=10)

        def req(rid, n=6):
            return PreprocessedRequest(
                model="mock", token_ids=[1, 2, 3, 4], request_id=rid,
                stop=StopConditions(max_tokens=n),
            )

        # Stuff worker 0: one running (slow), one queued (at the limit).
        ids = sorted(client.instance_ids())
        w0 = ids[0]
        s0 = await client.direct(w0, req("fill0", 400).to_wire())
        task0 = asyncio.create_task(s0.__anext__())
        for _ in range(200):
            await asyncio.sleep(0.005)
            if engines[0]._running:
                break
        s1 = await client.direct(w0, req("fill1", 4).to_wire())

        migration = Migration(
            client=client, push_router=None, mode="round_robin", limit=3
        )
        streams = []
        for i in range(4):
            toks = []
            async for out in migration.generate(req(f"m{i}", 6)):
                toks.extend(out.token_ids)
            streams.append(toks)
        expect = [97 + (i % 26) for i in range(6)]
        assert all(s == expect for s in streams), streams
        # At least one round-robin pick hit the stuffed worker and shed.
        assert engines[0].sched_stats["shed_total"] >= 1
        task0.cancel()
        with suppress(Exception):
            await task0
        with suppress(Exception):
            await s1.kill()
        await client.stop()
        await client_rt.shutdown()
    finally:
        for rt in rts:
            with suppress(ConnectionError, OSError):
                await rt.shutdown()
        await store.stop()


async def test_migration_does_not_retry_deadline_errors():
    """DeadlineExceededError is typed and final: the migration operator
    must pass it through without burning replay attempts."""
    from dynamo_tpu.llm.migration import MigrationOperator
    from dynamo_tpu.runtime.pipeline import PipelineBuilder

    calls = []

    class DeadlineBackend:
        async def generate(self, pre, ctx):
            calls.append(pre.request_id)
            raise DeadlineExceededError("deadline exceeded: test")
            yield  # pragma: no cover

    pipe = PipelineBuilder().link(MigrationOperator(limit=3)).backend(
        DeadlineBackend()
    )
    with pytest.raises(DeadlineExceededError):
        async for _ in pipe.generate(
            PreprocessedRequest(model="m", token_ids=[1], request_id="r"),
            Context(),
        ):
            pass
    assert calls == ["r"]  # exactly one attempt


async def test_disconnect_while_queued_cleans_engine_and_router():
    """Satellite e2e: cancel a request still in the scheduler queue —
    the worker drops the sequence, every block returns, and the router
    pin is freed."""
    from dynamo_tpu.llm.kv_router.protocols import RouterConfig
    from dynamo_tpu.llm.kv_router.router import KvPushRouter, KvRouter
    from dynamo_tpu.runtime import DistributedRuntime
    from dynamo_tpu.runtime.store import StoreServer

    store = StoreServer()
    await store.start()
    rt = await DistributedRuntime.create(store.address)
    client_rt = await DistributedRuntime.create(store.address)
    engine = MockTpuEngine(
        MockEngineArgs(
            num_kv_blocks=256, block_size=8, max_num_seqs=1,
            decode_us_per_seq=20000.0,
        )
    )
    try:
        ep = rt.namespace("dq").component("w").endpoint("generate")

        async def handler(req, ctx):
            async for out in engine.generate(req, ctx):
                yield out

        await ep.serve(handler)
        client = await (
            client_rt.namespace("dq").component("w").endpoint("generate").client()
        )
        await client.wait_for_instances(1, timeout=10)
        router = KvRouter(
            client_rt.store, "dq", "w", RouterConfig(use_kv_events=False, block_size=8)
        )
        push = KvPushRouter(client, router)

        async def stream(rid, max_tokens):
            payload = PreprocessedRequest(
                model="mock", token_ids=[1] * 16, request_id=rid,
                stop=StopConditions(max_tokens=max_tokens),
            ).to_wire()
            async for item in push.generate(
                payload, request_id=rid, token_ids=[1] * 16
            ):
                pass

        t1 = asyncio.create_task(stream("long", 300))
        for _ in range(200):
            await asyncio.sleep(0.005)
            if engine._running:
                break
        t2 = asyncio.create_task(stream("queued", 4))
        for _ in range(200):
            await asyncio.sleep(0.005)
            if len(engine._waiting):
                break
        assert len(engine._waiting) == 1
        assert "queued" in router.active._seqs
        t2.cancel()  # the client vanished mid-queue
        with suppress(asyncio.CancelledError):
            await t2
        for _ in range(400):
            await asyncio.sleep(0.005)
            if not len(engine._waiting):
                break
        assert not len(engine._waiting), "cancelled request stuck in queue"
        assert "queued" not in router.active._seqs, "router pin leaked"
        t1.cancel()
        with suppress(asyncio.CancelledError):
            await t1
        for _ in range(400):
            await asyncio.sleep(0.005)
            if engine.kv.free_blocks == engine.kv.capacity:
                break
        assert engine.kv.free_blocks == engine.kv.capacity, "blocks leaked"
        assert "long" not in router.active._seqs
        await client.stop()
    finally:
        with suppress(ConnectionError, OSError):
            await client_rt.shutdown()
        with suppress(ConnectionError, OSError):
            await rt.shutdown()
        await store.stop()


async def test_streaming_deadline_expiry_is_typed_503_e2e():
    """A STREAMING request that expires in the worker queue must answer
    a typed 503 — the frontend pulls the first chunk before committing
    the 200 SSE headers, so pre-first-token sheds keep the full error
    contract (status, code, Retry-After) instead of an in-band error."""
    import aiohttp

    from dynamo_tpu.backends.mocker.main import run_mocker
    from dynamo_tpu.frontend.main import run_frontend
    from dynamo_tpu.runtime import DistributedRuntime
    from dynamo_tpu.runtime.store import StoreServer

    store = StoreServer()
    await store.start()
    worker_rt = await DistributedRuntime.create(store.address)
    served = asyncio.Event()
    worker = asyncio.create_task(
        run_mocker(
            worker_rt, model_name="mock",
            engine_args=MockEngineArgs(
                num_kv_blocks=512, block_size=8, max_num_seqs=1,
                decode_us_per_seq=50000.0,
            ),
            served_event=served,
        )
    )
    await asyncio.wait_for(served.wait(), 30)
    front_rt = await DistributedRuntime.create(store.address)
    ready = asyncio.Event()
    services: list = []
    frontend = asyncio.create_task(
        run_frontend(
            front_rt, http_host="127.0.0.1", http_port=0, router_mode="kv",
            ready_event=ready, service_out=services,
        )
    )
    await asyncio.wait_for(ready.wait(), 30)
    base = f"http://127.0.0.1:{services[0].port}"
    try:
        async with aiohttp.ClientSession() as s:
            for _ in range(200):
                async with s.get(f"{base}/v1/models") as r:
                    if (await r.json())["data"]:
                        break
                await asyncio.sleep(0.05)
            url = f"{base}/v1/chat/completions"

            async def blocker():
                with suppress(Exception):
                    async with s.post(
                        url,
                        json={
                            "model": "mock", "stream": True,
                            "messages": [{"role": "user", "content": "x"}],
                            "max_tokens": 200, "temperature": 0,
                        },
                    ) as r:
                        async for _ in r.content:
                            pass

            t = asyncio.create_task(blocker())
            await asyncio.sleep(0.3)  # blocker occupies the single slot
            async with s.post(
                url,
                json={
                    "model": "mock", "stream": True,
                    "messages": [{"role": "user", "content": "late"}],
                    "max_tokens": 4, "temperature": 0,
                },
                headers={"x-request-deadline-ms": "200"},
            ) as r:
                assert r.status == 503, await r.text()
                assert "Retry-After" in r.headers
                err = (await r.json())["error"]
                assert err["type"] == "deadline_exceeded"
                assert err["code"] == "deadline" and err["retryable"] is True
            t.cancel()
            with suppress(asyncio.CancelledError):
                await t
    finally:
        frontend.cancel()
        worker.cancel()
        for task in (frontend, worker):
            with suppress(asyncio.CancelledError):
                await task
        for rt in (front_rt, worker_rt):
            with suppress(ConnectionError, OSError):
                await rt.shutdown()
        await store.stop()


# -- frontend e2e (admission + draining + chaos shed) -------------------------


async def test_frontend_overload_contract_e2e():
    """One fleet, the whole frontend contract: 429 + Retry-After on the
    tenant rate limit (per-tenant isolation), 503 at the in-flight
    ceiling, chaos-plan shed as clean 503, /health flips to draining,
    and admitted streams complete normally throughout."""
    import aiohttp

    from dynamo_tpu.backends.mocker.main import run_mocker
    from dynamo_tpu.frontend.main import run_frontend
    from dynamo_tpu.runtime import DistributedRuntime
    from dynamo_tpu.runtime.store import StoreServer

    store = StoreServer()
    await store.start()
    worker_rt = await DistributedRuntime.create(store.address)
    served = asyncio.Event()
    worker = asyncio.create_task(
        run_mocker(
            worker_rt, model_name="mock",
            engine_args=MockEngineArgs(
                num_kv_blocks=512, block_size=8, speedup_ratio=1000.0
            ),
            served_event=served,
        )
    )
    await asyncio.wait_for(served.wait(), 30)
    front_rt = await DistributedRuntime.create(store.address)
    ready = asyncio.Event()
    services: list = []
    frontend = asyncio.create_task(
        run_frontend(
            front_rt, http_host="127.0.0.1", http_port=0, router_mode="kv",
            ready_event=ready, service_out=services,
            admission=AdmissionConfig(tenant_rate=2.0, tenant_burst=2),
        )
    )
    await asyncio.wait_for(ready.wait(), 30)
    service = services[0]
    base = f"http://127.0.0.1:{service.port}"

    def body(stream=False, max_tokens=4):
        return {
            "model": "mock",
            "messages": [{"role": "user", "content": "hi"}],
            "max_tokens": max_tokens,
            "temperature": 0,
            "stream": stream,
        }

    try:
        async with aiohttp.ClientSession() as s:
            for _ in range(200):
                async with s.get(f"{base}/v1/models") as r:
                    if (await r.json())["data"]:
                        break
                await asyncio.sleep(0.05)

            url = f"{base}/v1/chat/completions"
            # Burst of 2 admits; the third 429s with Retry-After.
            for _ in range(2):
                async with s.post(url, json=body()) as r:
                    assert r.status == 200, await r.text()
            async with s.post(url, json=body()) as r:
                assert r.status == 429
                assert "Retry-After" in r.headers
                err = (await r.json())["error"]
                assert err["type"] == "rate_limit_error"
                assert err["code"] == "rate_limit" and err["retryable"] is True
            # Another tenant is unaffected (its own bucket).
            async with s.post(
                url, json=body(), headers={"x-tenant-id": "gold"}
            ) as r:
                assert r.status == 200, await r.text()
            # Shed counter visible on frontend /metrics.
            async with s.get(f"{base}/metrics") as r:
                text = await r.text()
            assert "frontend_requests_shed_total" in text
            assert 'reason="rate_limit"' in text

            # In-flight ceiling: retryable 503 at the cap.
            service.admission.config.max_inflight = 1
            service.admission.inflight = 1  # simulate one stuck request
            async with s.post(
                url, json=body(), headers={"x-tenant-id": "ceil"}
            ) as r:
                assert r.status == 503
                err = (await r.json())["error"]
                assert err["code"] == "queue_full" and err["retryable"] is True
            service.admission.inflight = 0

            # Malformed deadline header -> 400; valid one -> 200.
            async with s.post(
                url, json=body(),
                headers={"x-tenant-id": "d", "x-request-deadline-ms": "soon"},
            ) as r:
                assert r.status == 400
            async with s.post(
                url, json=body(),
                headers={"x-tenant-id": "d", "x-request-deadline-ms": "30000"},
            ) as r:
                assert r.status == 200, await r.text()

            # Chaos shed at frontend.admit: clean 503, never a 500.
            chaos.install(
                chaos.ChaosPlan(
                    rules=[
                        chaos.ChaosRule(
                            point="frontend.admit", action="drop", count=1
                        )
                    ]
                )
            )
            try:
                async with s.post(
                    url, json=body(), headers={"x-tenant-id": "cx"}
                ) as r:
                    assert r.status == 503
                    assert (await r.json())["error"]["retryable"] is True
                    assert "Retry-After" in r.headers
            finally:
                chaos.uninstall()

            # Draining: health goes dark and new requests shed.
            front_rt._draining = True
            async with s.get(f"{base}/health") as r:
                assert r.status == 503
                assert (await r.json())["status"] == "draining"
            async with s.post(
                url, json=body(), headers={"x-tenant-id": "dr"}
            ) as r:
                assert r.status == 503
                assert (await r.json())["error"]["code"] == "draining"
            front_rt._draining = False
            async with s.get(f"{base}/health") as r:
                assert r.status == 200
                assert (await r.json())["status"] == "healthy"
    finally:
        frontend.cancel()
        worker.cancel()
        for t in (frontend, worker):
            with suppress(asyncio.CancelledError):
                await t
        with suppress(ConnectionError, OSError):
            await front_rt.shutdown()
        with suppress(ConnectionError, OSError):
            await worker_rt.shutdown()
        await store.stop()
