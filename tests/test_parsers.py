"""Tool-call and reasoning parser tests (parity: reference lib/parsers)."""

import pytest

from dynamo_tpu.llm.parsers import (
    StreamingThinkParser,
    detect_format,
    parse_reasoning,
    parse_tool_calls,
)


def test_hermes():
    text = 'Sure!\n<tool_call>\n{"name": "get_weather", "arguments": {"city": "SF"}}\n</tool_call>'
    out = parse_tool_calls(text, "hermes")
    assert out.content == "Sure!"
    assert out.tool_calls[0].name == "get_weather"
    assert out.tool_calls[0].arguments == {"city": "SF"}
    assert out.tool_calls[0].to_openai()["function"]["name"] == "get_weather"


def test_hermes_multiple_calls():
    text = (
        '<tool_call>{"name": "a", "arguments": {}}</tool_call>'
        '<tool_call>{"name": "b", "arguments": {"x": 1}}</tool_call>'
    )
    out = parse_tool_calls(text, "hermes")
    assert [c.name for c in out.tool_calls] == ["a", "b"]
    assert out.content is None


def test_mistral():
    text = '[TOOL_CALLS][{"name": "search", "arguments": {"q": "tpu"}}]'
    out = parse_tool_calls(text, "mistral")
    assert out.tool_calls[0].name == "search"
    assert out.content is None


def test_llama3_json():
    text = '<|python_tag|>{"name": "lookup", "parameters": {"id": 7}}'
    out = parse_tool_calls(text, "llama3_json")
    assert out.tool_calls[0].name == "lookup"
    assert out.tool_calls[0].arguments == {"id": 7}


def test_pythonic():
    out = parse_tool_calls('[get_weather(city="SF", units="c"), ping()]', "pythonic")
    assert [c.name for c in out.tool_calls] == ["get_weather", "ping"]
    assert out.tool_calls[0].arguments == {"city": "SF", "units": "c"}


def test_pythonic_rejects_non_calls():
    out = parse_tool_calls("[1, 2, 3]", "pythonic")
    assert out.tool_calls == []
    assert out.content == "[1, 2, 3]"


def test_nemotron():
    text = '<TOOLCALL>[{"name": "f", "arguments": {"k": 2}}]</TOOLCALL>'
    out = parse_tool_calls(text, "nemotron")
    assert out.tool_calls[0].arguments == {"k": 2}


def test_json_arguments_as_string():
    text = '{"name": "f", "arguments": "{\\"a\\": 1}"}'
    out = parse_tool_calls(text, "json")
    assert out.tool_calls[0].arguments == {"a": 1}


def test_detect_format():
    assert detect_format("<tool_call>{}</tool_call>") == "hermes"
    assert detect_format("[TOOL_CALLS][]") == "mistral"
    assert detect_format('{"name": "x", "arguments": {}}') == "json"
    assert detect_format("plain text answer") is None


def test_unknown_parser_raises():
    with pytest.raises(ValueError):
        parse_tool_calls("x", "nope")


def test_reasoning_think_tags():
    out = parse_reasoning("<think>step 1. step 2.</think>The answer is 4.", "deepseek_r1")
    assert out.reasoning_content == "step 1. step 2."
    assert out.content == "The answer is 4."


def test_reasoning_missing_open_tag():
    out = parse_reasoning("reasoning here</think>answer", "deepseek_r1")
    assert out.reasoning_content == "reasoning here"
    assert out.content == "answer"


def test_reasoning_gpt_oss_channels():
    text = "<|channel|>analysis\nlet me think<|channel|>final\n42"
    out = parse_reasoning(text, "gpt_oss")
    assert out.reasoning_content == "let me think"
    assert out.content == "42"


def test_streaming_think_parser():
    p = StreamingThinkParser()
    chunks = ["<thi", "nk>ab", "c</th", "ink>he", "llo"]
    reasoning, content = "", ""
    for c in chunks:
        r, t = p.feed(c)
        reasoning += r
        content += t
    r, t = p.flush()
    reasoning += r
    content += t
    assert reasoning == "abc"
    assert content == "hello"


def test_streaming_without_think():
    p = StreamingThinkParser()
    r, t = p.feed("just an answer")
    r2, t2 = p.flush()
    assert r + r2 == ""
    assert t + t2 == "just an answer"
