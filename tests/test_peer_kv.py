"""Cross-worker KV visibility: a prefix cached (or offloaded) on worker
A is PULLABLE by worker B over the data plane instead of recomputed.

Reference parity: KVBM-distributed leader/worker
(`lib/llm/src/block_manager/distributed/leader.rs:64`) — the router's
radix view spans workers; when routing cannot land on the best-overlap
worker, the chosen worker onboards the peer's blocks (device tier or
host/disk offload tiers) through the ``kv_fetch`` endpoint.
"""

import asyncio

import aiohttp
import pytest

from dynamo_tpu.backends.jax.main import run_jax_worker
from dynamo_tpu.frontend.main import run_frontend
from dynamo_tpu.llm.protocols.common import (
    PreprocessedRequest,
    SamplingOptions,
    StopConditions,
)
from dynamo_tpu.runtime import DistributedRuntime
from dynamo_tpu.runtime.store import StoreServer

pytestmark = [pytest.mark.e2e, pytest.mark.pre_merge]


class PeerCluster:
    """N aggregated jax workers with tiny device pools + host/disk
    offload tiers, plus a frontend (KV routing). ``kv_dtype`` may be a
    single dtype or a per-worker list (mixed-fleet tests)."""

    def __init__(self, tmp_path, kv_dtype: "str | list[str]" = "bf16", n: int = 2):
        self.tmp_path = tmp_path
        self.n = n
        self.kv_dtypes = (
            list(kv_dtype) if isinstance(kv_dtype, list) else [kv_dtype] * n
        )
        self.store = StoreServer()
        self.runtimes: list[DistributedRuntime] = []
        self.worker_ids: list[int] = []
        self.cores: list = []
        self.tasks: list[asyncio.Task] = []
        self.service = None
        self.base_url = ""

    async def __aenter__(self) -> "PeerCluster":
        await self.store.start()
        for i in range(self.n):
            rt = await DistributedRuntime.create(self.store.address)
            self.runtimes.append(rt)
            served = asyncio.Event()
            self.tasks.append(
                asyncio.create_task(
                    run_jax_worker(
                        rt, model_name="peer", preset="tiny", seed=0,
                        served_event=served, core_out=self.cores,
                        engine_overrides={
                            "num_kv_blocks": 16,
                            "host_kv_blocks": 8,
                            "disk_kv_dir": str(self.tmp_path / f"disk{i}"),
                            "disk_kv_blocks": 64,
                            "kv_dtype": self.kv_dtypes[i],
                        },
                    )
                )
            )
            await asyncio.wait_for(served.wait(), 30)
            self.worker_ids.append(rt.primary_lease_id)
        front_rt = await DistributedRuntime.create(self.store.address)
        self.runtimes.append(front_rt)
        ready = asyncio.Event()
        services: list = []
        self.tasks.append(
            asyncio.create_task(
                run_frontend(
                    front_rt, http_host="127.0.0.1", http_port=0,
                    router_mode="kv", ready_event=ready, service_out=services,
                )
            )
        )
        await asyncio.wait_for(ready.wait(), 10)
        self.service = services[0]
        self.base_url = f"http://127.0.0.1:{self.service.port}"
        async with aiohttp.ClientSession() as s:
            for _ in range(200):
                async with s.get(f"{self.base_url}/v1/models") as r:
                    if (await r.json())["data"]:
                        return self
                await asyncio.sleep(0.05)
        raise TimeoutError("model never appeared")

    async def __aexit__(self, *exc) -> None:
        for rt in self.runtimes:
            rt.signal_shutdown()
        await asyncio.sleep(0.1)
        for t in self.tasks:
            t.cancel()
        for rt in self.runtimes:
            try:
                await rt.shutdown()
            # dynalint: allow-broad-except(best-effort teardown; runtime may already be closed)
            except Exception:
                pass
        await self.store.stop()


def _pre(prompt, rid, max_tokens=4):
    return PreprocessedRequest(
        model="peer", token_ids=list(prompt), request_id=rid,
        sampling=SamplingOptions(temperature=0.0),
        stop=StopConditions(max_tokens=max_tokens),
    )


async def _route(push_router, pre, **kw):
    toks = []
    async for out in push_router.generate(
        pre.to_wire(), pre.request_id, list(pre.token_ids), **kw
    ):
        toks.extend(out.get("token_ids") or [])
    push_router.router.free(pre.request_id)
    return toks


async def test_peer_pull_avoids_recompute_after_offload(tmp_path):
    """Worker A caches a prompt, overflows it down to its offload tiers;
    a request EXCLUDED from A (migration semantics) lands on B, which
    pulls the prefix from A's tiers and prefix-hits instead of
    recomputing (VERDICT r5 #8 done-bar)."""
    prompt = list(range(1, 90))  # 11 complete 8-token blocks
    async with PeerCluster(tmp_path) as c:
        served = c.service.manager.get("peer")
        assert served is not None and served.push_router is not None
        push = served.push_router
        a_id = c.worker_ids[0]
        a_core = c.cores[0]
        b_core = c.cores[1]

        # 1) Land the prompt on worker A (pinned for determinism).
        want = await _route(
            push, _pre(prompt, "seed"),
            router_overrides={"backend_instance_id": a_id},
        )
        assert len(want) == 4

        # 2) Overflow A's 16-block device pool so the prompt's blocks
        #    demote to host/disk (KV events stay 'stored': the worker can
        #    still serve them).
        for i in range(3):
            filler = list(range(100 + 40 * i, 140 + 40 * i))
            await _route(
                push, _pre(filler, f"fill{i}"),
                router_overrides={"backend_instance_id": a_id},
            )
        a_core.offload.flush()
        assert len(a_core.host_pool) + len(a_core.disk_pool) > 0, (
            "filler never pushed the prompt into the offload tiers"
        )

        # 3) Same prompt, A excluded: B must get the peer hint, pull the
        #    prefix, and answer identically with a prefix-cache hit.
        assert b_core.transfer_stats["imported_blocks"] == 0
        got = []
        cached = 0
        async for out in push.generate(
            _pre(prompt, "reroute").to_wire(), "reroute", list(prompt),
            exclude={a_id},
        ):
            got.extend(out.get("token_ids") or [])
            meta = out.get("meta") or {}
            cached = max(cached, meta.get("cached_tokens", 0))
        push.router.free("reroute")

        assert got == want, "peer-pulled decode diverged"
        assert b_core.transfer_stats["imported_blocks"] > 0, (
            "worker B never pulled the peer prefix"
        )
        assert cached > 0, "pulled prefix was not prefix-cache-hit"
        # The pull is non-destructive: A still holds its tiers.
        assert len(a_core.host_pool) + len(a_core.disk_pool) > 0


async def test_kv_fetch_serves_int8_packed_pages(tmp_path):
    """ISSUE 8: an int8 fleet's ``kv_fetch`` endpoint announces
    dtype="int8" in its geometry frame and streams the canonical packed
    pages (int8 bytes + scales) — byte-identical to the producer's
    device content — and the peer imports them verbatim and serves the
    prefix with the same greedy output. (Exercises the SERVER half of
    the peer pull directly; the asyncio.timeout client half is covered
    by test_peer_pull_avoids_recompute_after_offload on 3.11+.)"""
    from dynamo_tpu.tokens import compute_seq_hashes

    prompt = list(range(1, 90))  # 11 complete 8-token blocks
    async with PeerCluster(tmp_path, kv_dtype="int8") as c:
        served = c.service.manager.get("peer")
        push = served.push_router
        a_id = c.worker_ids[0]
        a_core, b_core = c.cores[0], c.cores[1]
        assert a_core.engine.kv_quantized

        want = await _route(
            push, _pre(prompt, "seed"),
            router_overrides={"backend_instance_id": a_id},
        )
        assert len(want) == 4

        bs = a_core.engine.block_size
        hashes = compute_seq_hashes(prompt, bs)[: (len(prompt) - 1) // bs]
        local = a_core.read_cached_pages(hashes)
        assert len(local) == len(hashes)

        fetch_client = await (
            c.runtimes[0].namespace("dynamo").component("backend")
            .endpoint("kv_fetch").client()
        )
        await fetch_client.wait_for_instances(2)
        stream = await fetch_client.direct(a_id, {"hashes": hashes})
        dtype = None
        pages: list[bytes] = []
        async for frame in stream:
            if "dtype" in frame:
                dtype = frame["dtype"]
            if "kv" in frame:
                pages.extend(frame["kv"])
        assert dtype == "int8", "geometry frame did not announce int8"
        assert [bytes(p) for p in pages] == local, (
            "wire pages diverged from the producer's device bytes"
        )

        # The consumer-side import (what _pull_peer_prefix does with
        # these frames) lands them bit-identically and serves the prefix.
        shape = [
            a_core.cfg.num_layers, bs,
            2 * a_core.cfg.num_kv_heads, a_core.cfg.head_dim,
        ]
        blocks = [
            {
                "hash": h,
                "parent": hashes[i - 1] if i else None,
                "shape": shape, "dtype": "int8", "kv": kv,
            }
            for i, (h, kv) in enumerate(zip(hashes, pages))
        ]
        res = b_core.import_blocks(blocks)
        assert res.imported == len(blocks) and res.dropped == 0
        assert b_core.read_cached_pages(hashes) == local
        got = await _route(
            push, _pre(prompt, "peer-serve"),
            router_overrides={"backend_instance_id": c.worker_ids[1]},
        )
        assert got == want, "int8 peer-served decode diverged"


async def test_three_worker_pool_shared_prefix_e2e(tmp_path):
    """ISSUE 11 three-worker pool: a shared prefix cached on worker A; a
    request EXCLUDED from A lands on one of B/C, which pulls the blocks
    from A over the dataplane and streams BIT-IDENTICALLY to A's cold
    prefill — while the third worker never touches the prefix."""
    prompt = list(range(1, 90))  # 11 complete 8-token blocks
    async with PeerCluster(tmp_path, n=3) as c:
        served = c.service.manager.get("peer")
        push = served.push_router
        a_id = c.worker_ids[0]
        a_core = c.cores[0]

        want = await _route(
            push, _pre(prompt, "seed"),
            router_overrides={"backend_instance_id": a_id},
        )
        assert len(want) == 4

        got = []
        async for out in push.generate(
            _pre(prompt, "reroute").to_wire(), "reroute", list(prompt),
            exclude={a_id},
        ):
            got.extend(out.get("token_ids") or [])
        push.router.free("reroute")
        assert got == want, "cross-worker pooled decode diverged"

        pulled = [
            core for core in c.cores[1:]
            if core.transfer_stats["imported_blocks"] > 0
        ]
        assert len(pulled) == 1, (
            "exactly one of B/C must have pulled the prefix: "
            f"{[core.transfer_stats for core in c.cores]}"
        )
        assert pulled[0].transfer_stats["imported_blocks"] >= 11
        # A still serves its copy (the pull is non-destructive).
        assert a_core.cached_prefix_tokens(prompt) > 0


async def test_mixed_dtype_fleet_pull_fails_fast_and_recomputes(tmp_path):
    """PR 8 dtype contract at the pool layer: a bf16 worker's pages must
    NOT import into an int8 worker (re-quantizing breaks bit-stability).
    The pull fails fast, the request completes via local recompute, and
    the recomputed prefix serves consistently afterwards."""
    prompt = list(range(1, 90))
    async with PeerCluster(tmp_path, kv_dtype=["bf16", "int8"]) as c:
        served = c.service.manager.get("peer")
        push = served.push_router
        a_id = c.worker_ids[0]
        b_core = c.cores[1]
        assert not c.cores[0].engine.kv_quantized
        assert b_core.engine.kv_quantized

        await _route(
            push, _pre(prompt, "seed"),
            router_overrides={"backend_instance_id": a_id},
        )
        got = await _route(push, _pre(prompt, "reroute"), exclude={a_id})
        assert len(got) == 4, "mixed-dtype fallback lost the stream"
        # The fail-fast contract: NOTHING imported across the dtype edge.
        assert b_core.transfer_stats["imported_blocks"] == 0
        # The fallback recompute cached the prefix locally: a pinned
        # repeat on B streams identically (its own quantized decode).
        got2 = await _route(
            push, _pre(prompt, "again"),
            router_overrides={"backend_instance_id": c.worker_ids[1]},
        )
        assert got2 == got, "post-fallback repeat diverged"


async def test_chaos_sever_mid_pull_degrades_to_recompute(tmp_path):
    """Acceptance chaos e2e (jax engines): the peer connection is severed
    MID-PULL (after the first frame); the request completes via local
    recompute with a stream bit-identical to the no-fault run — no
    wedged request, no stall."""
    from dynamo_tpu.runtime import chaos
    from dynamo_tpu.runtime.chaos import ChaosPlan, ChaosRule

    prompt = list(range(1, 90))
    try:
        async with PeerCluster(tmp_path) as c:
            served = c.service.manager.get("peer")
            push = served.push_router
            a_id = c.worker_ids[0]
            b_core = c.cores[1]

            want = await _route(
                push, _pre(prompt, "seed"),
                router_overrides={"backend_instance_id": a_id},
            )
            a_addr = c.runtimes[0].ingress.address
            chaos.install(ChaosPlan(rules=[
                ChaosRule(
                    point="dataplane.recv", action="sever",
                    match=a_addr, after=1,
                ),
            ]))
            got = await _route(push, _pre(prompt, "reroute"), exclude={a_id})
            chaos.uninstall()
            assert got == want, "sever mid-pull broke the stream"
            # At most the pre-sever chunk imported; the rest recomputed.
            assert b_core.transfer_stats["imported_blocks"] < 11
    finally:
        chaos.uninstall()
