"""Composable service-pipeline graph (runtime/pipeline.py).

Parity target: reference `lib/runtime/src/pipeline/nodes.rs` — operators
transform the forward (request) path, the backward (response) path, or
both; links assemble frontend→operators→backend; an assembled pipeline is
itself an engine (nestable). Plus the llm-layer composition: the
migration segment (MigrationOperator → RouterEgress) as a pipeline with
an extra operator linked in front.
"""

import asyncio

import pytest

from dynamo_tpu.runtime.engine import Context
from dynamo_tpu.runtime.pipeline import (
    FunctionOperator,
    PipelineBuilder,
    ServicePipeline,
)


class EchoBackend:
    """Yields its request n times (records what it actually received)."""

    def __init__(self, n=2):
        self.n = n
        self.seen = []

    async def generate(self, request, context):
        self.seen.append((request, dict(context.meta)))
        for i in range(self.n):
            yield f"{request}:{i}"


async def collect(stream):
    return [x async for x in stream]


def test_forward_and_backward_transforms_compose_in_order():
    backend = EchoBackend()
    pipe = (
        PipelineBuilder()
        .link(FunctionOperator(forward=lambda r, c: r + "+a"))
        .link(FunctionOperator(
            forward=lambda r, c: r + "+b",
            backward=lambda x, c: x.upper(),
        ))
        .link(FunctionOperator(backward=lambda x, c: x + "!"))
        .backend(backend)
    )
    out = asyncio.run(collect(pipe.generate("req", Context())))
    # Forward order a then b; backward order innermost-first (! before upper).
    assert backend.seen[0][0] == "req+a+b"
    assert out == ["REQ+A+B:0!", "REQ+A+B:1!"]


def test_operator_carries_forward_state_into_backward_path():
    """The load-bearing Operator property (reference nodes.rs doc): one
    node sees both paths of the same request — here, a retry operator
    replays with state accumulated from the partial response stream."""

    class FlakyBackend:
        def __init__(self):
            self.calls = []

        async def generate(self, request, context):
            self.calls.append(request)
            yield request + 1
            if len(self.calls) == 1:
                raise ConnectionError("worker died")
            yield request + 2

    class RetryOperator:
        async def generate(self, request, context, next):
            got = []
            while True:
                try:
                    async for item in next(request + sum(got), context):
                        got.append(item)
                        yield item
                    return
                except ConnectionError:
                    continue  # replay with forward state from backward path

    backend = FlakyBackend()
    pipe = PipelineBuilder().link(RetryOperator()).backend(backend)
    out = asyncio.run(collect(pipe.generate(10, Context())))
    # First attempt saw 10, yielded 11, died; retry saw 10+11=21.
    assert backend.calls == [10, 21]
    assert out == [11, 22, 23]


def test_short_circuit_without_calling_next():
    class CacheOperator:
        async def generate(self, request, context, next):
            if request == "cached":
                yield "hit"
                return
            async for item in next(request, context):
                yield item

    backend = EchoBackend(n=1)
    pipe = PipelineBuilder().link(CacheOperator()).backend(backend)
    assert asyncio.run(collect(pipe.generate("cached", Context()))) == ["hit"]
    assert backend.seen == []
    assert asyncio.run(collect(pipe.generate("miss", Context()))) == ["miss:0"]


def test_pipeline_nests_as_backend():
    inner = PipelineBuilder().link(
        FunctionOperator(backward=lambda x, c: f"[{x}]")
    ).backend(EchoBackend(n=1))
    outer = PipelineBuilder().link(
        FunctionOperator(forward=lambda r, c: r + "-outer")
    ).backend(inner)
    assert isinstance(inner, ServicePipeline)
    out = asyncio.run(collect(outer.generate("x", Context())))
    assert out == ["[x-outer:0]"]


def test_bare_async_function_as_backend():
    async def backend_fn(request, context):
        yield request * 2

    pipe = PipelineBuilder().backend(backend_fn)
    assert asyncio.run(collect(pipe.generate(21, Context()))) == [42]


def test_context_meta_flows_to_backend():
    class HintOperator:
        async def generate(self, request, context, next):
            ctx = context.child()
            ctx.meta["exclude_instances"] = {7}
            async for item in next(request, ctx):
                yield item

    backend = EchoBackend(n=1)
    pipe = PipelineBuilder().link(HintOperator()).backend(backend)
    asyncio.run(collect(pipe.generate("r", Context())))
    assert backend.seen[0][1]["exclude_instances"] == {7}


def test_migration_segment_is_a_pipeline_with_front_operators():
    """The llm migration segment composes like any other graph: an audit
    operator linked in FRONT of MigrationOperator sees the original
    request once while the egress (downstream of migration) sees the
    replayed request after a mid-stream worker death."""
    from dynamo_tpu.llm.migration import Migration
    from dynamo_tpu.llm.protocols.common import (
        LLMEngineOutput,
        PreprocessedRequest,
        SamplingOptions,
        StopConditions,
    )

    class FlakyClient:
        """EndpointClient stand-in: first worker dies mid-stream."""

        def __init__(self):
            self.dispatches = []

        def pick_instance(self, mode, exclude):
            return 2 if 1 in exclude else 1

        async def direct(self, worker_id, payload, headers=None):
            self.dispatches.append((worker_id, list(payload["token_ids"])))

            async def stream():
                yield LLMEngineOutput(token_ids=[100]).to_wire()
                if worker_id == 1:
                    raise ConnectionError("conn reset")
                yield LLMEngineOutput(
                    token_ids=[101], finish_reason="stop"
                ).to_wire()

            return stream()

    audited = []

    class AuditOperator:
        async def generate(self, request, context, next):
            audited.append(list(request.token_ids))
            async for item in next(request, context):
                yield item

    client = FlakyClient()
    m = Migration(client=client, push_router=None, mode="round_robin", limit=2)
    pipe = m.build_pipeline(AuditOperator())
    pre = PreprocessedRequest(
        model="t", token_ids=[1, 2, 3], request_id="r1",
        sampling=SamplingOptions(), stop=StopConditions(max_tokens=8),
    )

    async def run():
        from dynamo_tpu.runtime.engine import Context as Ctx

        return [o async for o in pipe.generate(pre, Ctx(request_id="r1"))]

    out = asyncio.run(run())
    assert [o.token_ids for o in out] == [[100], [100], [101]]
    assert out[-1].finish_reason == "stop"
    # Audit (upstream of migration) saw the ORIGINAL request once; the
    # egress saw the replay with the streamed token appended and the
    # failed worker excluded.
    assert audited == [[1, 2, 3]]
    assert client.dispatches == [(1, [1, 2, 3]), (2, [1, 2, 3, 100])]
