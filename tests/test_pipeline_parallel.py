"""Pipeline parallelism on the virtual 8-device CPU mesh.

The GPipe shard_map program (parallel/pipeline.py) must produce the SAME
last-token logits and the SAME paged cache as the single-device
`model.forward_tokens` — including when microbatch boundaries cut through
the middle of a sequence (chunked-prefill causality across rounds), and
when decode steps ride the pipe.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dynamo_tpu.engine.config import EngineConfig, ModelConfig
from dynamo_tpu.engine.model import (
    decode_tokens,
    forward_tokens,
    init_cache,
    init_cache_stacked,
    init_params,
)
from dynamo_tpu.parallel.pipeline import (
    cache_sharding_pp,
    make_pp_mesh,
    plan_microbatches,
    pp_forward_tokens,
    pp_param_specs,
    shard_params_pp,
)

CFG = ModelConfig(
    name="pp-test",
    vocab_size=512,
    hidden_size=64,
    intermediate_size=128,
    num_layers=4,
    num_heads=8,
    num_kv_heads=8,
    head_dim=16,
    dtype="float32",
    tie_embeddings=True,
)
ENG = EngineConfig(
    num_kv_blocks=32,
    block_size=8,
    max_num_seqs=8,
    max_model_len=128,
    prefill_buckets=(64,),
    decode_buckets=(4, 8),
)


def build_wave(seq_lens: list[int], pad_to: int, rng: np.random.RandomState):
    """Multi-sequence ragged prefill wave, the EngineCore layout: returns
    (global numpy operands dict, per-seq block ids)."""
    S = len(seq_lens)
    bs = ENG.block_size
    T = sum(seq_lens)
    assert pad_to >= T
    tokens = np.zeros(pad_to, np.int32)
    positions = np.zeros(pad_to, np.int32)
    write_pages = np.full(pad_to, ENG.garbage_block, np.int32)
    write_offs = np.zeros(pad_to, np.int32)
    tables = np.full((S, ENG.max_blocks_per_seq), ENG.garbage_block, np.int32)
    cu = np.zeros(S + 1, np.int32)
    next_block = 0
    for s, n in enumerate(seq_lens):
        lo = cu[s]
        cu[s + 1] = lo + n
        tokens[lo : lo + n] = rng.randint(1, CFG.vocab_size, size=n)
        pos = np.arange(n, dtype=np.int32)
        positions[lo : lo + n] = pos
        n_blocks = (n + bs - 1) // bs
        ids = np.arange(next_block, next_block + n_blocks, dtype=np.int32)
        next_block += n_blocks
        tables[s, :n_blocks] = ids
        write_pages[lo : lo + n] = ids[pos // bs]
        write_offs[lo : lo + n] = pos % bs
    return {
        "tokens": tokens,
        "positions": positions,
        "write_pages": write_pages,
        "write_offs": write_offs,
        "kv_lens": np.asarray(seq_lens, np.int32),
        "block_tables": tables,
        "cu_q_lens": cu,
        "num_seqs": np.asarray([S], np.int32),
        "last_rows": (cu[1:] - 1).astype(np.int32),
    }


def single_device_prefill(params, wave):
    cache = init_cache(CFG, ENG)
    logits, cache = forward_tokens(
        params, cache,
        jnp.asarray(wave["tokens"]), jnp.asarray(wave["positions"]),
        jnp.asarray(wave["write_pages"]), jnp.asarray(wave["write_offs"]),
        jnp.asarray(wave["kv_lens"]), jnp.asarray(wave["block_tables"]),
        jnp.asarray(wave["cu_q_lens"]), jnp.asarray(wave["num_seqs"]),
        jnp.asarray(wave["last_rows"]), CFG, ENG, None,
    )
    return logits, cache


def pp_prefill(params_pp, cache_pp, wave, mesh, n_micro):
    plan = plan_microbatches(
        wave["tokens"], wave["positions"], wave["write_pages"],
        wave["write_offs"], wave["kv_lens"], wave["cu_q_lens"],
        int(wave["num_seqs"][0]), wave["last_rows"], n_micro,
        ENG.garbage_block,
    )
    return pp_forward_tokens(
        params_pp, cache_pp,
        jnp.asarray(plan.tokens), jnp.asarray(plan.positions),
        jnp.asarray(plan.write_pages), jnp.asarray(plan.write_offs),
        jnp.asarray(plan.kv_lens), jnp.asarray(wave["block_tables"]),
        jnp.asarray(plan.cu_q_lens), jnp.asarray(wave["num_seqs"]),
        jnp.asarray(plan.last_local), jnp.asarray(plan.last_mask),
        cfg=CFG, engine=ENG, mesh=mesh, n_micro=plan.n_micro,
    )


@pytest.mark.parametrize("n_micro", [1, 3])
def test_pp_prefill_matches_single_device(n_micro):
    """Microbatch boundaries cut mid-sequence (lens 20/13/9, Tm=14 at
    M=3): per-chunk kv_lens must give chunked-prefill causality, and the
    drained logits + the full layer-sharded cache must match."""
    rng = np.random.RandomState(0)
    wave = build_wave([20, 13, 9], pad_to=42, rng=rng)
    params = init_params(jax.random.PRNGKey(0), CFG)
    want_logits, want_cache = single_device_prefill(params, wave)

    mesh = make_pp_mesh(4)
    params_pp = shard_params_pp(params, CFG, mesh)
    cache_pp = jax.device_put(
        init_cache_stacked(CFG, ENG), cache_sharding_pp(mesh)
    )
    got_logits, got_cache = pp_prefill(params_pp, cache_pp, wave, mesh, n_micro)

    np.testing.assert_allclose(
        np.asarray(got_logits), np.asarray(want_logits), rtol=2e-4, atol=2e-4
    )
    # Garbage page excluded: both paths scribble pad/bubble writes there
    # (its content is unspecified by contract; nothing reads it unmasked).
    # want_cache is the engine's per-layer tuple; got_cache is pp-stacked.
    real = slice(0, ENG.num_kv_blocks)
    want_stacked = np.stack([np.asarray(c) for c in want_cache])
    np.testing.assert_allclose(
        np.asarray(got_cache)[:, real], want_stacked[:, real],
        rtol=2e-4, atol=2e-4,
    )


def test_pp_decode_step_matches_single_device():
    """A decode step (one token per sequence) rides the same pipe: PP
    prefill then PP decode vs single-device prefill + decode_tokens."""
    rng = np.random.RandomState(1)
    lens = [20, 13, 9]
    wave = build_wave(lens, pad_to=42, rng=rng)
    params = init_params(jax.random.PRNGKey(0), CFG)
    want_logits, want_cache = single_device_prefill(params, wave)

    B = 4  # decode bucket (one pad lane)
    nxt = np.zeros(B, np.int32)
    nxt[:3] = np.argmax(np.asarray(want_logits), axis=-1)
    tables = np.full((B, ENG.max_blocks_per_seq), ENG.garbage_block, np.int32)
    tables[:3] = wave["block_tables"]
    pos = np.zeros(B, np.int32)
    pos[:3] = lens
    active = np.zeros(B, bool)
    active[:3] = True
    want_d, _ = decode_tokens(
        params, want_cache, jnp.asarray(nxt), jnp.asarray(tables),
        jnp.asarray(pos), jnp.asarray(active), CFG, ENG, None,
    )

    mesh = make_pp_mesh(4)
    params_pp = shard_params_pp(params, CFG, mesh)
    cache_pp = jax.device_put(
        init_cache_stacked(CFG, ENG), cache_sharding_pp(mesh)
    )
    _, cache_pp = pp_prefill(params_pp, cache_pp, wave, mesh, 3)

    # Decode wave in the ragged layout: B rows, q_len 1 each.
    bs = ENG.block_size
    wp = np.where(active, tables[np.arange(B), pos // bs], ENG.garbage_block)
    dec = {
        "tokens": nxt,
        "positions": pos,
        "write_pages": wp.astype(np.int32),
        "write_offs": (pos % bs).astype(np.int32),
        "kv_lens": np.where(active, pos + 1, 1).astype(np.int32),
        "block_tables": tables,
        "cu_q_lens": np.arange(B + 1, dtype=np.int32),
        "num_seqs": np.asarray([B], np.int32),
        "last_rows": np.arange(B, dtype=np.int32),
    }
    got_d, _ = pp_prefill(params_pp, cache_pp, dec, mesh, 2)
    np.testing.assert_allclose(
        np.asarray(got_d)[:3], np.asarray(want_d)[:3], rtol=2e-4, atol=2e-4
    )


def test_pp_param_specs_reject_bad_layer_split():
    with pytest.raises(ValueError, match="divide num_layers"):
        pp_param_specs(CFG, 3)


def test_engine_core_pp_matches_single_device():
    """The REAL EngineCore on a pp=4 mesh — GPipe prefill waves plus the
    wavefront decode chain — produces identical greedy output, including
    a non-greedy seeded lane (sampler feedback rides the ring)."""
    from dynamo_tpu.engine.core import EngineCore
    from dynamo_tpu.llm.protocols.common import (
        PreprocessedRequest,
        SamplingOptions,
        StopConditions,
    )

    def run(pp_mesh):
        core = EngineCore(CFG, ENG, seed=0, pp_mesh=pp_mesh)
        reqs = [
            PreprocessedRequest(
                model="t",
                token_ids=list(range(3 + i, 40 + 3 * i)),
                request_id=f"r{i}",
                sampling=SamplingOptions(
                    temperature=0.0 if i < 2 else 0.8, seed=7,
                ),
                stop=StopConditions(max_tokens=6, ignore_eos=True),
            )
            for i in range(3)
        ]
        seqs = [core.add_request(r) for r in reqs]
        done: dict[str, list[int]] = {s.request_id: [] for s in seqs}
        fins: dict[str, str] = {}
        for _ in range(300):
            for seq, out in core.step():
                done[seq.request_id].extend(out.token_ids)
                if out.finish_reason:
                    fins[seq.request_id] = out.finish_reason
            if len(fins) == 3:
                break
        assert len(fins) == 3
        return done

    assert run(make_pp_mesh(4)) == run(None)


def test_engine_core_pp_logprobs_match_single_device():
    """Logprobs ride the wavefront chain (vocab-sharded lm head + the
    banked per-round (te, ge) scatter) — values must match the
    unpipelined engine's."""
    from dynamo_tpu.engine.core import EngineCore
    from dynamo_tpu.llm.protocols.common import (
        OutputOptions,
        PreprocessedRequest,
        SamplingOptions,
        StopConditions,
    )

    def run(pp_mesh):
        core = EngineCore(CFG, ENG, seed=0, pp_mesh=pp_mesh)
        seq = core.add_request(
            PreprocessedRequest(
                model="t", token_ids=list(range(5, 30)), request_id="r",
                sampling=SamplingOptions(temperature=0.0),
                stop=StopConditions(max_tokens=5, ignore_eos=True),
                output=OutputOptions(logprobs=2),
            )
        )
        lps: list[dict] = []
        for _ in range(100):
            for s, out in core.step():
                if out.logprobs:
                    lps.extend(out.logprobs)
            if seq.finish is not None:
                return lps
        raise AssertionError("never finished")

    want = run(None)
    got = run(make_pp_mesh(4))
    assert [e["token_id"] for e in got] == [e["token_id"] for e in want]
    for g, w in zip(got, want):
        assert abs(g["logprob"] - w["logprob"]) < 1e-3
        assert [t for t, _ in g["top"]] == [t for t, _ in w["top"]]


def test_engine_core_pp_rejects_bad_buckets():
    import dataclasses

    from dynamo_tpu.engine.core import EngineCore

    bad = dataclasses.replace(ENG, decode_buckets=(6,))
    with pytest.raises(ValueError, match="decode bucket"):
        EngineCore(CFG, bad, seed=0, pp_mesh=make_pp_mesh(4))
