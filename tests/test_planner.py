"""SLA planner: predictors, interpolators, replica math, sinusoidal dry run.

Parity: reference planner dry-run tests
(`components/planner/test/planner_sla_dryrun.py`) driven by
`benchmarks/sin_load_generator` traces.
"""

import math

import numpy as np
import pytest

from dynamo_tpu.planner import (
    ARPredictor,
    ConstantPredictor,
    DecodeInterpolator,
    MovingAveragePredictor,
    Observation,
    Planner,
    PlannerConfig,
    PrefillInterpolator,
    RecordingConnector,
    SlaTargets,
    from_profile,
)

PROFILE = {
    # One replica: TTFT grows with ISL; ITL grows with concurrency.
    "prefill": {"isl": [128, 512, 2048, 8192], "ttft_s": [0.02, 0.06, 0.2, 0.9]},
    "decode": {"concurrency": [1, 8, 32, 64], "itl_s": [0.01, 0.012, 0.02, 0.045]},
}


def make_planner(connector=None, **cfg):
    p, d = from_profile(PROFILE)
    return Planner(
        p, d,
        connector or RecordingConnector(),
        sla=SlaTargets(ttft_s=0.2, itl_s=0.02),
        config=PlannerConfig(predictor=cfg.pop("predictor", "constant"), **cfg),
    )


def test_predictors_track_load():
    for cls in (ConstantPredictor, MovingAveragePredictor, ARPredictor):
        pred = cls()
        for v in [1, 2, 3, 4, 5, 6, 7, 8]:
            pred.observe(v)
        assert pred.predict() > 0

    # AR follows a linear ramp beyond the last value.
    ar = ARPredictor()
    for v in range(1, 40):
        ar.observe(float(v))
    assert ar.predict() > 38.0


def test_interpolators():
    p, d = from_profile(PROFILE)
    assert p.ttft_at(128) == pytest.approx(0.02)
    assert 0.06 < p.ttft_at(1024) < 0.2
    assert p.max_isl_within(0.2) == 2048
    assert d.max_concurrency_within(0.02) == 32
    assert d.throughput_at(32) == pytest.approx(32 / 0.02)


def test_replica_math_scales_with_rate():
    planner = make_planner()
    low = planner.compute_plan(Observation(request_rate=1, mean_isl=512, mean_osl=128))
    high = planner.compute_plan(Observation(request_rate=20, mean_isl=512, mean_osl=128))
    assert high.prefill_replicas > low.prefill_replicas
    assert high.decode_replicas > low.decode_replicas
    assert low.prefill_replicas >= 1


def test_correction_factor_inflates_replicas():
    planner = make_planner()
    obs = Observation(request_rate=10, mean_isl=512, mean_osl=128)
    base = planner.compute_plan(obs)
    # Live TTFT 3x worse than profile -> correction kicks in.
    planner2 = make_planner()
    slow = Observation(
        request_rate=10, mean_isl=512, mean_osl=128, observed_ttft_s=0.18
    )
    worse = planner2.compute_plan(slow)
    assert worse.correction_prefill > 1.5
    assert worse.prefill_replicas >= base.prefill_replicas


async def test_sinusoidal_dryrun_scales_up_and_down():
    connector = RecordingConnector()
    planner = make_planner(connector, predictor="constant", max_replicas=32)

    # Sinusoidal request rate (the reference's sin_load_generator shape).
    t = np.linspace(0, 2 * math.pi, 48)
    rates = 10 + 9 * np.sin(t)
    decode_counts = []
    for r in rates:
        plan = planner.compute_plan(
            Observation(request_rate=float(r), mean_isl=512, mean_osl=256)
        )
        await planner.apply(plan)
        decode_counts.append(plan.decode_replicas)

    assert max(decode_counts) > min(decode_counts), "planner never scaled"
    # Scaling decisions follow the wave: peak replicas around the rate peak.
    peak_idx = int(np.argmax(rates))
    trough_idx = int(np.argmin(rates))
    assert decode_counts[peak_idx] > decode_counts[trough_idx]
    assert connector.current("decode") == decode_counts[-1]


@pytest.mark.integration
async def test_local_process_connector_scales_real_workers():
    """set_replicas spawns/terminates worker processes and the discovery
    plane follows — the single-host analogue of the reference's
    KubernetesConnector patching deployment replicas."""
    import asyncio

    from dynamo_tpu.planner.connector import LocalProcessConnector
    from dynamo_tpu.runtime import DistributedRuntime
    from dynamo_tpu.runtime.store import StoreServer

    async with StoreServer() as server:
        conn = LocalProcessConnector(
            server.address,
            worker_argv={
                "backend": [
                    "-m", "dynamo_tpu.backends.mocker",
                    "--model-name", "scaletest", "--speedup-ratio", "100",
                ]
            },
        )
        rt = await DistributedRuntime.create(server.address)
        client = await (
            rt.namespace("dynamo").component("backend").endpoint("generate").client()
        )
        try:
            await conn.set_replicas("backend", 2)
            for _ in range(300):
                if len(client.instance_ids()) == 2:
                    break
                await asyncio.sleep(0.1)
            assert len(client.instance_ids()) == 2
            assert conn.current("backend") == 2

            await conn.set_replicas("backend", 1)
            for _ in range(300):
                if len(client.instance_ids()) == 1:
                    break
                await asyncio.sleep(0.1)
            assert len(client.instance_ids()) == 1
            assert conn.current("backend") == 1
        finally:
            conn.shutdown()
            await client.stop()
            await rt.shutdown()
