"""SLA planner: predictors, interpolators, replica math, sinusoidal dry run.

Parity: reference planner dry-run tests
(`components/planner/test/planner_sla_dryrun.py`) driven by
`benchmarks/sin_load_generator` traces.
"""

import math

import numpy as np
import pytest

from dynamo_tpu.planner import (
    ARPredictor,
    ConstantPredictor,
    DecodeInterpolator,
    MovingAveragePredictor,
    Observation,
    Planner,
    PlannerConfig,
    PrefillInterpolator,
    RecordingConnector,
    SlaTargets,
    from_profile,
)

PROFILE = {
    # One replica: TTFT grows with ISL; ITL grows with concurrency.
    "prefill": {"isl": [128, 512, 2048, 8192], "ttft_s": [0.02, 0.06, 0.2, 0.9]},
    "decode": {"concurrency": [1, 8, 32, 64], "itl_s": [0.01, 0.012, 0.02, 0.045]},
}


def make_planner(connector=None, **cfg):
    p, d = from_profile(PROFILE)
    return Planner(
        p, d,
        connector or RecordingConnector(),
        sla=SlaTargets(ttft_s=0.2, itl_s=0.02),
        config=PlannerConfig(predictor=cfg.pop("predictor", "constant"), **cfg),
    )


def test_predictors_track_load():
    for cls in (ConstantPredictor, MovingAveragePredictor, ARPredictor):
        pred = cls()
        for v in [1, 2, 3, 4, 5, 6, 7, 8]:
            pred.observe(v)
        assert pred.predict() > 0

    # AR follows a linear ramp beyond the last value.
    ar = ARPredictor()
    for v in range(1, 40):
        ar.observe(float(v))
    assert ar.predict() > 38.0


def test_interpolators():
    p, d = from_profile(PROFILE)
    assert p.ttft_at(128) == pytest.approx(0.02)
    assert 0.06 < p.ttft_at(1024) < 0.2
    assert p.max_isl_within(0.2) == 2048
    assert d.max_concurrency_within(0.02) == 32
    assert d.throughput_at(32) == pytest.approx(32 / 0.02)


def test_replica_math_scales_with_rate():
    planner = make_planner()
    low = planner.compute_plan(Observation(request_rate=1, mean_isl=512, mean_osl=128))
    high = planner.compute_plan(Observation(request_rate=20, mean_isl=512, mean_osl=128))
    assert high.prefill_replicas > low.prefill_replicas
    assert high.decode_replicas > low.decode_replicas
    assert low.prefill_replicas >= 1


def test_correction_factor_inflates_replicas():
    planner = make_planner()
    obs = Observation(request_rate=10, mean_isl=512, mean_osl=128)
    base = planner.compute_plan(obs)
    # Live TTFT 3x worse than profile -> correction kicks in.
    planner2 = make_planner()
    slow = Observation(
        request_rate=10, mean_isl=512, mean_osl=128, observed_ttft_s=0.18
    )
    worse = planner2.compute_plan(slow)
    assert worse.correction_prefill > 1.5
    assert worse.prefill_replicas >= base.prefill_replicas


async def test_sinusoidal_dryrun_scales_up_and_down():
    connector = RecordingConnector()
    planner = make_planner(connector, predictor="constant", max_replicas=32)

    # Sinusoidal request rate (the reference's sin_load_generator shape).
    t = np.linspace(0, 2 * math.pi, 48)
    rates = 10 + 9 * np.sin(t)
    decode_counts = []
    for r in rates:
        plan = planner.compute_plan(
            Observation(request_rate=float(r), mean_isl=512, mean_osl=256)
        )
        await planner.apply(plan)
        decode_counts.append(plan.decode_replicas)

    assert max(decode_counts) > min(decode_counts), "planner never scaled"
    # Scaling decisions follow the wave: peak replicas around the rate peak.
    peak_idx = int(np.argmax(rates))
    trough_idx = int(np.argmin(rates))
    assert decode_counts[peak_idx] > decode_counts[trough_idx]
    assert connector.current("decode") == decode_counts[-1]


@pytest.mark.integration
async def test_local_process_connector_scales_real_workers():
    """set_replicas spawns/terminates worker processes and the discovery
    plane follows — the single-host analogue of the reference's
    KubernetesConnector patching deployment replicas."""
    import asyncio

    from dynamo_tpu.planner.connector import LocalProcessConnector
    from dynamo_tpu.runtime import DistributedRuntime
    from dynamo_tpu.runtime.store import StoreServer

    async with StoreServer() as server:
        conn = LocalProcessConnector(
            server.address,
            worker_argv={
                "backend": [
                    "-m", "dynamo_tpu.backends.mocker",
                    "--model-name", "scaletest", "--speedup-ratio", "100",
                ]
            },
        )
        rt = await DistributedRuntime.create(server.address)
        client = await (
            rt.namespace("dynamo").component("backend").endpoint("generate").client()
        )
        try:
            await conn.set_replicas("backend", 2)
            for _ in range(300):
                if len(client.instance_ids()) == 2:
                    break
                await asyncio.sleep(0.1)
            assert len(client.instance_ids()) == 2
            assert conn.current("backend") == 2

            await conn.set_replicas("backend", 1)
            for _ in range(300):
                if len(client.instance_ids()) == 1:
                    break
                await asyncio.sleep(0.1)
            assert len(client.instance_ids()) == 1
            assert conn.current("backend") == 1
        finally:
            conn.shutdown()
            await client.stop()
            await rt.shutdown()


# -- ISSUE 14 satellites ----------------------------------------------------


def test_ar_predictor_on_ramp():
    """A linear ramp must be extrapolated BEYOND the last observation —
    the anticipation the closed-loop controller leans on at diurnal
    upswings."""
    ar = ARPredictor(order=4)
    for v in range(10, 60):
        ar.observe(float(v))
    pred = ar.predict()
    assert pred > 59.0, f"ramp not extrapolated: {pred}"
    assert pred < 80.0, f"ramp wildly overshot: {pred}"


def test_ar_predictor_on_seasonal():
    """On a sinusoid the AR fit must track the wave, not the mean: the
    prediction at a rising zero-crossing exceeds the prediction at a
    falling one."""
    import numpy as np

    period = 32

    def run_until(phase_idx: int) -> float:
        ar = ARPredictor(window=128, order=8)
        for i in range(phase_idx):
            ar.observe(10.0 + 8.0 * math.sin(2 * math.pi * i / period))
        return ar.predict()

    rising = run_until(3 * period)            # next value heads up
    falling = run_until(3 * period + period // 2)
    assert rising > falling
    # And the fit is tight on a clean signal: within the wave's envelope.
    assert 1.0 < rising < 19.0


def test_ar_predictor_constant_and_stability():
    """A constant signal predicts (approximately) itself, forever — no
    drift, no blow-up, never negative."""
    ar = ARPredictor(order=4)
    for _ in range(200):
        ar.observe(7.5)
    for _ in range(20):
        p = ar.predict()
        assert p == pytest.approx(7.5, abs=0.5)
        ar.observe(7.5)
    # Decaying-to-zero load must never produce a negative rate.
    ar2 = ARPredictor(order=4)
    for v in [50.0, 20.0, 5.0, 1.0, 0.2, 0.0, 0.0, 0.0, 0.0, 0.0]:
        ar2.observe(v)
    assert ar2.predict() >= 0.0


def test_ar_predictor_window_shorter_than_order():
    """Fewer observations than the AR order: fall back to
    last-value (and 0.0 on a cold start) instead of a degenerate fit."""
    ar = ARPredictor(order=8)
    assert ar.predict() == 0.0
    for v in (3.0, 4.0):
        ar.observe(v)
    assert ar.predict() == 4.0
    # Exactly order+1 observations is still too few for the lstsq rows.
    for v in range(7):
        ar.observe(float(v))
    assert ar.predict() == 6.0


def test_parse_prometheus_keeps_labeled_series_addressable():
    """ISSUE 14 satellite: labeled samples of one family must stay
    individually addressable (the controller reads per-worker and
    per-tenant series directly) while the family total still sums."""
    from dynamo_tpu.planner.observer import parse_prometheus

    text = "\n".join(
        [
            "# HELP dynamo_queue_depth Queued requests",
            "# TYPE dynamo_queue_depth gauge",
            'dynamo_queue_depth{namespace="dynamo",worker_id="7"} 3',
            'dynamo_queue_depth{namespace="dynamo",worker_id="9"} 5',
            'dynamo_tenant_shed_total{tenant="acme"} 2',
            'dynamo_tenant_shed_total{tenant="gumbo"} 4',
            "dynamo_requests_total 11",
        ]
    )
    t = parse_prometheus(text)
    # Family totals (labels collapsed) keep the historical diff math.
    assert t["dynamo_queue_depth"] == 8.0
    assert t["dynamo_tenant_shed_total"] == 6.0
    assert t["dynamo_requests_total"] == 11.0
    # Labeled samples stay addressable exactly as written on the wire.
    assert t['dynamo_queue_depth{namespace="dynamo",worker_id="7"}'] == 3.0
    assert t['dynamo_queue_depth{namespace="dynamo",worker_id="9"}'] == 5.0
    assert t['dynamo_tenant_shed_total{tenant="acme"}'] == 2.0


def test_connector_sigterm_drain_and_reap():
    """ISSUE 14 satellite: scale-down sends SIGTERM (graceful drain),
    reaps exit codes (no zombies), and only escalates to SIGKILL when a
    worker overstays the drain window."""
    import signal
    import time as _time

    from dynamo_tpu.planner.connector import LocalProcessConnector
    import asyncio

    async def scenario():
        # Cooperative child: default SIGTERM disposition -> exits at once.
        conn = LocalProcessConnector(
            "unused:0",
            worker_argv={"w": ["-c", "import time; time.sleep(120)"]},
            drain_timeout_s=10.0,
        )
        try:
            await conn.set_replicas("w", 2)
            assert conn.current("w") == 2
            await conn.set_replicas("w", 1)
            assert conn.current("w") == 1
            deadline = _time.monotonic() + 10.0
            while conn.draining_count() and _time.monotonic() < deadline:
                await asyncio.sleep(0.05)
            assert conn.draining_count() == 0, "drained child never reaped"
            assert conn.kills_escalated == 0
            assert len(conn.exit_codes) == 1
            _, rc = conn.exit_codes[0]
            assert rc == -signal.SIGTERM, f"expected SIGTERM exit, got {rc}"
        finally:
            conn.shutdown()
        # Every child's exit code collected by shutdown: zombie-free.
        assert len(conn.exit_codes) == 2

        # Wedged child: ignores SIGTERM -> escalated to SIGKILL after
        # the (short) drain window.
        conn2 = LocalProcessConnector(
            "unused:0",
            worker_argv={
                "w": [
                    "-c",
                    "import signal, time; "
                    "signal.signal(signal.SIGTERM, signal.SIG_IGN); "
                    "time.sleep(120)",
                ]
            },
            drain_timeout_s=0.5,
        )
        try:
            await conn2.set_replicas("w", 1)
            # Let the child install its signal handler before TERMing it.
            await asyncio.sleep(1.0)
            await conn2.set_replicas("w", 0)
            deadline = _time.monotonic() + 15.0
            while conn2.draining_count() and _time.monotonic() < deadline:
                await asyncio.sleep(0.05)
            assert conn2.draining_count() == 0, "escalation never landed"
            assert conn2.kills_escalated == 1
            assert any(rc == -signal.SIGKILL for _, rc in conn2.exit_codes), (
                conn2.exit_codes
            )
        finally:
            conn2.shutdown()

    asyncio.run(scenario())
