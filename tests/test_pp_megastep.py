"""Fused pp megasteps + quantization composition (ISSUE 20).

The tentpole contract: on a pp mesh the decode chain runs INSIDE the
scanned device body — the ``lax.ppermute`` stage hop rides the megastep
scan with M microbatch groups interleaved as a wavefront, sampling /
stop flags / feedback gathers live on device, and the stop state is
psum-replicated — so k fused iterations cost ONE dispatch instead of k
host round-trips per stage. The invariant is the same as every other
fast-path feature: the token stream is BIT-IDENTICAL pp=N vs pp=1 and
fused vs single-step, across greedy + seeded temperature (+ top-p +
logprobs), waves + chunked scheduling, async execution on and off, EOS
inside a fused pp megastep, host-only stops at megastep boundaries, and
block pressure.

The composition satellites: int8 weights and int8 KV pages now shard
per stage (the construction-time ValueErrors are lifted), the canonical
packed ``{kv, scale}`` buffer contract is unchanged on pp workers (the
tier round trip below pins byte identity at every hop), and the combos
that are genuinely unsupported (spec decode, MoE dispatch, pp x tp)
keep pointed construction errors.
"""

import asyncio

import numpy as np
import pytest

import jax

from dynamo_tpu import tracing
from dynamo_tpu.engine import EngineCore, tiny_engine, tiny_model
from dynamo_tpu.engine.config import EngineConfig, ModelConfig
from dynamo_tpu.engine.core import MEGASTEP_WATCH_W
from dynamo_tpu.engine.model import init_params_quantized
from dynamo_tpu.llm.protocols.common import (
    PreprocessedRequest,
    SamplingOptions,
    StopConditions,
)
from dynamo_tpu.parallel.pipeline import make_pp_mesh

pytestmark = [pytest.mark.unit]

# 4 layers / vocab 512: stages evenly over pp in {2, 4} (tiny_model has
# only 2 layers, so it caps at pp=2 — it drives the tier round trip).
CFG = ModelConfig(
    name="pp-mega-test",
    vocab_size=512,
    hidden_size=64,
    intermediate_size=128,
    num_layers=4,
    num_heads=8,
    num_kv_heads=8,
    head_dim=16,
    dtype="float32",
    tie_embeddings=True,
)


def _eng(**kw) -> EngineConfig:
    base = dict(
        num_kv_blocks=32,
        block_size=8,
        max_num_seqs=8,
        max_model_len=128,
        prefill_buckets=(64,),
        decode_buckets=(4, 8),
    )
    base.update(kw)
    return EngineConfig(**base)


def make_core(pp: int, quant: bool = False, seed: int = 0, **kw) -> EngineCore:
    params = (
        init_params_quantized(jax.random.PRNGKey(0), CFG) if quant else None
    )
    return EngineCore(
        CFG, _eng(**kw), params=params, seed=seed,
        pp_mesh=make_pp_mesh(pp) if pp > 1 else None,
    )


def _req(prompt, rid, max_tokens=8, temperature=0.0, seed=None, top_p=1.0,
         logprobs=None, **stop_kw):
    pre = PreprocessedRequest(
        model="t",
        token_ids=prompt,
        request_id=rid,
        sampling=SamplingOptions(temperature=temperature, seed=seed,
                                 top_p=top_p),
        stop=StopConditions(max_tokens=max_tokens, **stop_kw),
    )
    if logprobs is not None:
        pre.output.logprobs = logprobs
    return pre


def drive(core, seqs, max_steps=4000):
    done = {s.request_id: [] for s in seqs}
    fins: dict[str, str] = {}
    lps = {s.request_id: [] for s in seqs}
    for _ in range(max_steps):
        for s, out in core.step():
            done[s.request_id].extend(out.token_ids)
            if out.logprobs:
                lps[s.request_id].extend(out.logprobs)
            if out.finish_reason:
                fins[s.request_id] = out.finish_reason
        if len(fins) == len(seqs) and not core.has_work():
            break
    return done, fins, lps


def _assert_streams_match(got, ref):
    """Token streams and finish reasons must be BIT-identical. Logprob
    FLOATS get tolerance: the pp lm head is vocab-sharded, so the
    log-softmax normalizer reduces in a different order than the
    single-device program — last-ULP drift on reported alternates is
    expected and does not touch sampling (token ids still match
    exactly)."""
    gd, gf, gl = got
    rd, rf, rl = ref
    assert gd == rd
    assert gf == rf
    assert set(gl) == set(rl)
    for rid in rl:
        assert len(gl[rid]) == len(rl[rid])
        for a, b in zip(gl[rid], rl[rid]):
            assert a["token_id"] == b["token_id"]
            assert a["logprob"] == pytest.approx(b["logprob"], abs=1e-4)
            assert [t for t, _ in a["top"]] == [t for t, _ in b["top"]]
            for (_, la), (_, lb) in zip(a["top"], b["top"]):
                assert la == pytest.approx(lb, abs=1e-4)


def _workload(core):
    """Greedy + seeded-temperature + top-p/logprobs lanes with staggered
    budgets, plus one long prompt (prefill waves / chunks between fused
    pp megasteps)."""
    rng = np.random.RandomState(0)
    seqs = [
        core.add_request(_req(
            list(range(i + 3, i + 30)), f"g{i}", max_tokens=8 + i,
            ignore_eos=True,
        ))
        for i in range(2)
    ]
    seqs.append(core.add_request(_req(
        [3, 5, 7, 9], "t", max_tokens=11, temperature=0.8, seed=11,
        ignore_eos=True,
    )))
    seqs.append(core.add_request(_req(
        [2, 4, 6, 8, 10], "p", max_tokens=9, temperature=0.9, seed=13,
        top_p=0.8, logprobs=3, ignore_eos=True,
    )))
    seqs.append(core.add_request(_req(
        list(rng.randint(1, 400, size=50)), "long", max_tokens=6,
        ignore_eos=True,
    )))
    return seqs


# -- bit-identical parity: pp on/off x fused on/off ---------------------------


@pytest.mark.parametrize(
    "pp",
    [2, pytest.param(4, marks=pytest.mark.slow)],  # pp=4 in tier-1 via the
)                                                  # int8+kvint8 compose test
def test_parity_fused_pp_vs_single_device(pp):
    """The acceptance invariant: pp=N with fused k=4 megasteps AND pp=N
    forced single-step both stream bit-identically to the unpipelined
    single-step engine — greedy, seeded temperature, top-p, and logprob
    lanes in one batch."""

    def run(p, k):
        core = make_core(p, megastep_k=k)
        out = drive(core, _workload(core))
        return out, core

    ref, _ = run(1, 1)
    got_single, _ = run(pp, 1)
    got_fused, core = run(pp, 4)
    _assert_streams_match(got_single, ref)
    _assert_streams_match(got_fused, ref)
    assert core.exec_stats["pp_fused_dispatches"] >= 1


def test_parity_pp_chunked_scheduling():
    """Chunked token-budget scheduling composes with pp (the old
    construction guard is lifted): mixed chunk+decode iterations run as
    single pp steps, decode-only iterations fuse — stream identical to
    the unpipelined single-step chunked engine."""

    def run(p, k):
        core = make_core(
            p, megastep_k=k, scheduling="chunked", prefill_chunk=32,
            max_num_batched_tokens=64,
        )
        return drive(core, _workload(core))

    _assert_streams_match(run(2, 4), run(1, 1))


@pytest.mark.slow
def test_parity_pp_async_composition():
    """pp x async-exec compose: one fused pp dispatch in flight while
    the next plans against the optimistic overlay — stream identical to
    the synchronous unpipelined loop (async OFF on the pp engine is the
    parity test above)."""

    def run(p, k, ae):
        core = make_core(p, megastep_k=k, async_exec=ae)
        return drive(core, _workload(core))

    _assert_streams_match(run(2, 4, True), run(1, 1, False))


# -- stops inside / at the boundary of a fused pp megastep --------------------


@pytest.mark.slow
def test_eos_inside_fused_pp_megastep():
    """A seeded lane that samples EOS at an inner wavefront iteration of
    a fused pp megastep finishes with reason 'eos' mid-megastep — the
    device stop flags see it on the drain stage, the psum-replicated
    alive state masks its remaining wavefront slots, and the stream
    matches the unpipelined single-step engine exactly; batch neighbors
    are untouched."""
    probe = make_core(1, megastep_k=1)
    s = probe.add_request(_req(
        [1, 2, 3], "p", max_tokens=12, temperature=0.9, seed=42,
        ignore_eos=True,
    ))
    d, _, _ = drive(probe, [s])
    eos = d["p"][4]  # mid-stream token -> EOS lands INSIDE a k=8 megastep
    if eos in d["p"][:4]:
        pytest.skip("seeded stream repeats before position 4")

    def run(p, k):
        core = EngineCore(
            CFG, _eng(megastep_k=k), seed=0, eos_token_ids=(eos,),
            pp_mesh=make_pp_mesh(p) if p > 1 else None,
        )
        seqs = [
            core.add_request(_req(
                [1, 2, 3], "e", max_tokens=12, temperature=0.9, seed=42,
            )),
            core.add_request(_req([9, 9, 9], "n", max_tokens=12,
                                  ignore_eos=True)),
        ]
        return drive(core, seqs)[:2]

    d1, f1 = run(1, 1)
    d8, f8 = run(2, 8)
    assert d1 == d8
    assert f1 == f8
    assert f8["e"] == "eos"
    assert d8["e"] == d["p"][:5]  # stopped mid-megastep, not at a boundary


def test_host_only_stop_forces_single_and_rolls_back_on_pp():
    """A stop watch WIDER than the device's MEGASTEP_WATCH_W slots is
    the one documented un-fused path — on a pp engine it must force the
    decode chain to k=1 (host stop-scan authority between dispatches),
    surface on the pp_forced_single gauge, and still match the
    unpipelined stream and finish reason exactly."""
    probe = make_core(1, megastep_k=1)
    s = probe.add_request(_req(
        [9, 9, 9], "p", max_tokens=20, temperature=0.9, seed=7,
        ignore_eos=True,
    ))
    d, _, _ = drive(probe, [s])
    stop_tok = d["p"][5]
    if stop_tok in d["p"][:5]:
        pytest.skip("seeded stream repeats before position 5")
    stop_ids = list(range(300, 300 + MEGASTEP_WATCH_W)) + [stop_tok]

    def run(p, k):
        core = make_core(p, megastep_k=k)
        seq = core.add_request(_req(
            [9, 9, 9], "x", max_tokens=20, temperature=0.9, seed=7,
            stop_token_ids=stop_ids, ignore_eos=True,
        ))
        out = drive(core, [seq])[:2]
        assert core.allocator._partials == 0
        return out, core

    ref, _ = run(1, 1)
    got, core = run(2, 8)
    assert got == ref == ({"x": d["p"][:6]}, {"x": "stop"})
    assert core.exec_stats["pp_fused_dispatches"] == 0
    assert core.exec_stats["pp_forced_single"] >= 1


# -- block pressure on a pp engine --------------------------------------------


@pytest.mark.slow
def test_block_pressure_drain_preempt_on_pp_engine():
    """k tokens of per-lane block headroom are reserved at plan time on
    the pp path too: pressure surfaces as drain -> preempt BEFORE the
    fused pp dispatch (never as mid-megastep exhaustion), and the
    preempted-and-replayed stream still matches an unpressured
    unpipelined single-step run."""

    def run(p, blocks, k):
        core = make_core(p, num_kv_blocks=blocks, max_model_len=64,
                         megastep_k=k)
        seqs = [
            core.add_request(_req(list(range(1, 17)), "a", max_tokens=24,
                                  ignore_eos=True)),
            core.add_request(_req(list(range(20, 36)), "b", max_tokens=24,
                                  ignore_eos=True)),
        ]
        done, fins, _ = drive(core, seqs, max_steps=8000)
        assert core.allocator._partials == 0
        return done, fins, core

    ref = run(1, 64, 1)[:2]
    d, f, core = run(2, 7, 8)
    assert (d, f) == ref
    assert core.sched_stats["preemptions"] >= 1


# -- quantization composition -------------------------------------------------


@pytest.mark.parametrize(
    "pp",
    [pytest.param(2, marks=pytest.mark.slow), 4],  # pp=2 compose in tier-1
)                                                  # via the tier round trip
def test_int8_weights_and_kv_compose_with_pp(pp):
    """The lifted carve-out, both quantizations at once: int8 weight
    pages AND packed {kv, scale} int8 KV shard per stage, the engine
    constructs (no ValueError), serves fused pp megasteps, and streams
    bit-identically to the unpipelined int8+kvint8 engine."""

    def run(p, k):
        core = make_core(p, quant=True, kv_dtype="int8", megastep_k=k)
        out = drive(core, _workload(core))
        return out, core

    ref, _ = run(1, 1)
    got, core = run(pp, 4)
    _assert_streams_match(got, ref)
    assert core.exec_stats["pp_fused_dispatches"] >= 1
    # The stacked quantized cache: ONE {kv, scale} dict, layer axis first.
    assert isinstance(core.cache, dict)
    assert set(core.cache) == {"kv", "scale"}
    assert core.cache["kv"].shape[0] == CFG.num_layers


def test_kvint8_pp_tier_round_trip_is_byte_stable(tmp_path):
    """THE round-trip satellite on a pp stage: int8 KV blocks written by
    the pp engine evict -> host tier -> disk tier -> onboard back to
    device BYTE-identically (the canonical packed buffer from PR 8 is
    unchanged under pp — quantize once, never re-quantize), and the
    onboarded prefix serves the same stream."""
    from dynamo_tpu.engine.kv_quant import unpack_kv_page
    from tests.test_host_kv_tier import _fill_with_noise

    t_cfg = tiny_model()
    mesh = make_pp_mesh(2)  # tiny preset has 2 layers -> pp=2

    def t_core(**kw):
        return EngineCore(
            t_cfg, tiny_engine(kv_dtype="int8", **kw), seed=0, pp_mesh=mesh,
        )

    prompt = list(range(7, 7 + 40))
    base = t_core()
    ref, _, _ = drive(base, [base.add_request(_req(prompt, "ref",
                                                   max_tokens=6))])

    core = t_core(
        num_kv_blocks=24, host_kv_blocks=4,
        disk_kv_dir=str(tmp_path / "g3"), disk_kv_blocks=256,
        max_model_len=128,
    )
    s1 = core.add_request(_req(prompt, "a", max_tokens=6))
    drive(core, [s1])
    bs = core.engine.block_size
    cap = (len(prompt) - 1) // bs
    prefix_hashes = s1.prompt_hashes[:cap]
    # Hop 0: canonical packed bytes while device-resident on the pipe.
    w0 = core.read_cached_pages(prefix_hashes)
    assert len(w0) == cap
    geom = core._page_geometry()
    for buf in w0:
        unpack_kv_page(buf, *geom)  # parses at the local geometry

    # Hop 1+2: evict through host into disk.
    _fill_with_noise(core, n_requests=8)
    _fill_with_noise(core, n_requests=8, tag=2000)
    core.offload.flush()
    in_host = [h for h in prefix_hashes if h in core.host_pool]
    in_disk = [h for h in prefix_hashes if h in core.disk_pool]
    assert in_host or in_disk, "noise did not push the prefix off-device"
    for i, h in enumerate(prefix_hashes):
        if h in core.host_pool:
            assert core.host_pool._blocks[h].kv.tobytes() == w0[i], (
                "host-tier bytes diverged from the pp-stage device write"
            )
        if h in core.disk_pool:
            assert core.disk_pool.peek(h).tobytes() == w0[i], (
                "disk-tier bytes diverged from the pp-stage device write"
            )

    # Hop 3: onboard back onto the pipe (admission prefix hit).
    s2 = core.add_request(_req(prompt, "b", max_tokens=6))
    d2, _, _ = drive(core, [s2])
    assert core.host_pool.stats.onboards + core.disk_pool.stats.onboards > 0
    assert s2.num_cached_tokens > 0
    assert d2["b"] == ref["ref"], "output changed across the tier round trip"
    w1 = core.read_cached_pages(prefix_hashes)
    assert w1 == w0, "onboarded device bytes diverged from the original"


# -- construction matrix: lifted composition vs pointed errors ----------------


def test_lifted_combos_construct():
    """Both directions pinned, the 'now works' half: every combo the
    first pp cut rejected at construction now builds a working engine."""
    make_core(2, quant=True)                      # int8 weights + pp
    make_core(2, kv_dtype="int8")                 # int8 KV + pp
    make_core(2, scheduling="chunked", prefill_chunk=32,
              max_num_batched_tokens=64)          # chunked + pp
    make_core(2, async_exec=True)                 # async + pp
    make_core(2, quant=True, kv_dtype="int8", async_exec=True,
              megastep_k=8)                       # all of it at once


def test_unsupported_combos_keep_pointed_errors():
    """The 'still rejected' half: genuinely unsupported combos fail at
    construction with pointed messages, not deep shard-setup errors."""
    with pytest.raises(ValueError, match="speculative decoding"):
        make_core(2, spec_decode="ngram", spec_k=4)
    with pytest.raises(ValueError, match="mutually exclusive"):
        from dynamo_tpu.parallel.sharding import make_mesh

        EngineCore(CFG, _eng(), seed=0, mesh=make_mesh(dp=1, tp=2),
                   pp_mesh=make_pp_mesh(2))
    with pytest.raises(ValueError, match="decode bucket"):
        make_core(4, decode_buckets=(6,))


def test_multihost_pp_cli_guard():
    """pp on the multihost leader/follower path stays a pointed CLI
    error (the one genuinely unsupported deployment shape named by the
    issue)."""
    from dynamo_tpu.backends.jax.main import run_jax_worker

    with pytest.raises(ValueError, match="--pp .* --nnodes"):
        asyncio.run(run_jax_worker(None, nnodes=2, pp=2))


# -- observability ------------------------------------------------------------


def test_pp_gauges_and_megastep_span():
    """scheduler_pp_* gauge sources and the pp_stages span attr: fused
    pp dispatches and pipe occupancy export on scheduler_stats, and
    every engine_megastep span carries pp_stages."""
    tracing.configure(enabled=True, sample=1.0)
    collector = tracing.get_collector()
    collector.clear()
    core = make_core(2, megastep_k=8)
    seq = core.add_request(_req([1, 2, 3], "m", max_tokens=16,
                                ignore_eos=True))
    drive(core, [seq])
    spans = [s for s in collector.stats() if s.name == "engine_megastep"]
    assert spans, "engine_megastep span missing"
    assert all(s.attrs["pp_stages"] == 2 for s in spans)
    st = core.scheduler_stats()
    assert st["pp_stages"] == 2
    assert st["pp_fused_dispatches"] >= 1
    # k*M wavefront items over k*M + pp - 1 rounds.
    k = max(1, core.engine.megastep)
    km = k * core._pp_micro
    assert st["pp_pipe_occupancy"] == pytest.approx(km / (km + 1))
    # Unpipelined engines report the trivial pipe.
    st1 = make_core(1).scheduler_stats()
    assert st1["pp_stages"] == 1
    assert st1["pp_pipe_occupancy"] == 1.0


# -- the A/B bar --------------------------------------------------------------


def test_pp_megastep_ab_holds_the_bar_live():
    """The acceptance A/B, run live on the mocker virtual clock:
    bench.run_pp_megastep_ab internally asserts all four arms stream
    identically, the k=1 pipe reports forced-single and the k=8 pipe
    only fused dispatches, and the relay pp=4 k=8 TPOT p50 lands at
    <= 0.5x the host-rollback baseline."""
    import sys
    from pathlib import Path

    sys.path.insert(0, str(Path(__file__).resolve().parents[1]))
    import bench

    r = bench.run_pp_megastep_ab()
    assert r["value"] <= 0.5
    rows = {row["config"]: row for row in r["rows"]}
    assert rows["relay-pp4-k8"]["tpot_p50_vs_k1"] <= 0.5


def test_bench_r14_recorded_and_holds_the_bar():
    """The acceptance numbers are pinned IN THE REPO: BENCH_r14.json is
    the recorded run of bench.run_pp_megastep_ab, re-asserted here so a
    regression that silently weakens the recorded claim fails tier-1."""
    import json
    from pathlib import Path

    r = json.loads(
        (Path(__file__).resolve().parents[1] / "BENCH_r14.json").read_text()
    )
    assert r["value"] <= 0.5
    rows = {row["config"]: row for row in r["rows"]}
    fused = rows["relay-pp4-k8"]
    base = rows["relay-pp4-k1"]
    assert fused["tpot_p50_vs_k1"] <= 0.5
    assert fused["pp_fused_dispatches"] > 0 and fused["pp_forced_single"] == 0
    assert base["pp_forced_single"] > 0 and base["pp_fused_dispatches"] == 0
    assert fused["pp_pipe_occupancy"] > base["pp_pipe_occupancy"]
    assert fused["dispatches_per_token"] < base["dispatches_per_token"]


# -- mocker mirror ------------------------------------------------------------


def _mock_pp_sim(pp: int, k: int, B=8, isl=64, osl=16):
    from dynamo_tpu.llm.mocker.engine import MockEngineArgs, MockTpuEngine, _Seq
    from dynamo_tpu.tokens import TokenBlockSequence, compute_seq_hashes

    args = MockEngineArgs(
        num_kv_blocks=1024, block_size=32, max_num_seqs=B,
        max_num_batched_tokens=2048, enable_prefix_caching=False,
        megastep_k=k, pp=pp,
    )
    eng = MockTpuEngine(args)
    seqs = []
    for j in range(B):
        prompt = [1 + (j % 7)] * isl
        s = _Seq(
            request_id=f"s{j}", prompt=prompt, max_tokens=osl,
            out=asyncio.Queue(),
            seq=TokenBlockSequence(prompt, args.block_size),
            prompt_hashes=compute_seq_hashes(prompt, args.block_size),
            stop=StopConditions(max_tokens=osl, ignore_eos=True),
        )
        seqs.append(s)
        eng._waiting.append(s)
    streams: dict[str, list[int]] = {s.request_id: [] for s in seqs}
    pp_rounds: list[int] = []
    while any(s in eng._running or s in eng._waiting for s in seqs):
        eng._admit()
        eng._step()
        pp_rounds.append(eng._last_pp_rounds)
        for s in seqs:
            while not s.out.empty():
                item = s.out.get_nowait()
                if isinstance(item, dict) and item.get("token_ids"):
                    streams[s.request_id].extend(item["token_ids"])
    return streams, pp_rounds, eng


def test_mocker_pp_stream_identical_and_hops_priced():
    """The mocker mirror: pp never changes token values (stream
    bit-identical to pp=1), decode dispatches price k*pp + pp - 1 stage
    hops on the virtual clock, and the scheduler_pp_* gauge sources
    mirror the real engine's."""
    from dynamo_tpu import knobs
    from dynamo_tpu.llm.mocker.engine import MockEngineArgs, MockTpuEngine

    with pytest.raises(ValueError, match="pp"):
        MockTpuEngine(MockEngineArgs(pp=0))

    s_ref, rounds_ref, eng_ref = _mock_pp_sim(1, 1)
    s_pp1, rounds1, eng1 = _mock_pp_sim(4, 1)
    s_pp8, rounds8, eng8 = _mock_pp_sim(4, 8)
    assert s_pp1 == s_ref and s_pp8 == s_ref
    assert set(rounds_ref) == {0}  # pp off: no hops ever priced
    # Host-rollback baseline: bubble per token; fused: bubble per k.
    assert max(rounds1) == 1 * 4 + 3
    assert max(rounds8) == 8 * 4 + 3
    st1, st8 = eng1.scheduler_stats(), eng8.scheduler_stats()
    assert st1["pp_stages"] == st8["pp_stages"] == 4
    assert st1["pp_forced_single"] > 0 and st1["pp_fused_dispatches"] == 0
    assert st8["pp_fused_dispatches"] > 0 and st8["pp_forced_single"] == 0
    assert st8["pp_pipe_occupancy"] > st1["pp_pipe_occupancy"]
    # The hop price lands on the virtual clock (and only under pp).
    base = eng_ref.iter_time_s(0, 8)
    hop = knobs.get_float("DYN_PP_HOP_US")
    assert eng_ref.iter_time_s(0, 8, pp_rounds=35) == pytest.approx(
        base + 35 * hop / 1e6
    )
