"""KV-event recorder round trip + workload generators."""

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

from benchmarks.prefix_synthesizer import (  # noqa: E402
    PrefixWorkloadConfig,
    analyze_prefix_reuse,
    synthesize,
)
from benchmarks.sin_load import SinLoadConfig, arrival_times, rate_trace  # noqa: E402
from dynamo_tpu.llm.kv_router.indexer import RadixTree  # noqa: E402
from dynamo_tpu.llm.kv_router.protocols import KvCacheEvent, RouterEvent  # noqa: E402
from dynamo_tpu.llm.kv_router.recorder import (  # noqa: E402
    KvEventRecorder,
    replay_events,
    replay_into,
)


def _stored(worker, eid, hashes, parent=None):
    return RouterEvent(worker, eid, KvCacheEvent("stored", tuple(hashes), parent))


def test_recorder_roundtrip(tmp_path):
    path = tmp_path / "events.jsonl"
    events = [
        _stored(1, 1, [10, 20, 30]),
        _stored(2, 1, [10, 20]),
        RouterEvent(1, 2, KvCacheEvent("removed", (30,), None)),
    ]
    with KvEventRecorder(path) as rec:
        for ev in events:
            rec.record(ev)
    assert rec.recorded == 3

    replayed = [ev for _, ev in replay_events(path)]
    assert replayed == events

    tree = RadixTree()
    assert replay_into(path, tree) == 3
    assert tree.find_matches([10, 20, 30]) == {1: 2, 2: 2}


def test_prefix_synthesizer_produces_shared_prefixes():
    wl = synthesize(PrefixWorkloadConfig(num_requests=50, seed=3))
    assert len(wl.prompts) == 50
    stats = analyze_prefix_reuse(wl.prompts, block_size=32)
    # Radix-shaped corpus: substantial reuse, but suffixes stay unique.
    assert stats["reuse_fraction"] > 0.3
    assert stats["unique_blocks"] < stats["total_blocks"]


def test_prefix_synthesizer_deterministic():
    a = synthesize(PrefixWorkloadConfig(num_requests=10, seed=7))
    b = synthesize(PrefixWorkloadConfig(num_requests=10, seed=7))
    assert a.prompts == b.prompts


def test_sin_load_trace_shape():
    cfg = SinLoadConfig(duration_s=300, period_s=300, mean_rps=5, amplitude_rps=4)
    trace = rate_trace(cfg)
    rates = [r for _, r in trace]
    assert max(rates) > 8
    assert min(rates) < 2
    arr = arrival_times(cfg)
    assert len(arr) > 0
    assert all(arr[i] <= arr[i + 1] for i in range(len(arr) - 1))
