"""Ring attention over the 8-device CPU mesh vs single-device reference."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dynamo_tpu.ops.ring_attention import (
    causal_attention_reference,
    ring_attention,
    sequence_parallel_mesh,
)


@pytest.mark.parametrize("T,n_q,n_kv,d", [(256, 8, 4, 16), (64, 4, 4, 32)])
def test_ring_matches_reference(T, n_q, n_kv, d):
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    q = jax.random.normal(ks[0], (T, n_q, d), jnp.float32)
    k = jax.random.normal(ks[1], (T, n_kv, d), jnp.float32)
    v = jax.random.normal(ks[2], (T, n_kv, d), jnp.float32)

    want = causal_attention_reference(q, k, v)
    mesh = sequence_parallel_mesh(8)
    got = ring_attention(q, k, v, mesh)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5)


def test_ring_rejects_indivisible():
    mesh = sequence_parallel_mesh(8)
    q = jnp.zeros((30, 4, 16))
    with pytest.raises(ValueError):
        ring_attention(q, q, q, mesh)


def test_ring_under_jit():
    mesh = sequence_parallel_mesh(8)
    ks = jax.random.split(jax.random.PRNGKey(1), 3)
    q = jax.random.normal(ks[0], (128, 4, 16), jnp.float32)
    k = jax.random.normal(ks[1], (128, 4, 16), jnp.float32)
    v = jax.random.normal(ks[2], (128, 4, 16), jnp.float32)
    got = jax.jit(lambda a, b, c: ring_attention(a, b, c, mesh))(q, k, v)
    want = causal_attention_reference(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5)
