"""Ring attention over the 8-device CPU mesh vs single-device reference."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dynamo_tpu.ops.ring_attention import (
    causal_attention_reference,
    ring_attention,
    sequence_parallel_mesh,
)


@pytest.mark.parametrize("T,n_q,n_kv,d", [(256, 8, 4, 16), (64, 4, 4, 32)])
def test_ring_matches_reference(T, n_q, n_kv, d):
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    q = jax.random.normal(ks[0], (T, n_q, d), jnp.float32)
    k = jax.random.normal(ks[1], (T, n_kv, d), jnp.float32)
    v = jax.random.normal(ks[2], (T, n_kv, d), jnp.float32)

    want = causal_attention_reference(q, k, v)
    mesh = sequence_parallel_mesh(8)
    got = ring_attention(q, k, v, mesh)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5)


def test_ring_rejects_indivisible():
    mesh = sequence_parallel_mesh(8)
    q = jnp.zeros((30, 4, 16))
    with pytest.raises(ValueError):
        ring_attention(q, q, q, mesh)


def test_ring_under_jit():
    mesh = sequence_parallel_mesh(8)
    ks = jax.random.split(jax.random.PRNGKey(1), 3)
    q = jax.random.normal(ks[0], (128, 4, 16), jnp.float32)
    k = jax.random.normal(ks[1], (128, 4, 16), jnp.float32)
    v = jax.random.normal(ks[2], (128, 4, 16), jnp.float32)
    got = jax.jit(lambda a, b, c: ring_attention(a, b, c, mesh))(q, k, v)
    want = causal_attention_reference(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5)


def test_engine_ring_prefill_matches_paged_waves():
    """Long-context serving: a prompt over ring_prefill_threshold runs as
    ONE dense sequence-parallel ring-attention pass that also fills the
    paged cache; greedy output (prefill token + paged decode continuation)
    must equal the plain engine's exactly. The reference has no sequence
    parallelism at all (SURVEY.md §2.6)."""
    import numpy as np

    from dynamo_tpu.engine import EngineCore, tiny_engine, tiny_model
    from dynamo_tpu.ops.ring_attention import sequence_parallel_mesh
    from tests.test_engine_core import _req, run_to_completion

    cfg = tiny_model()
    prompt = list(np.random.RandomState(3).randint(1, 300, size=100))

    base = EngineCore(cfg, tiny_engine(), seed=0)
    sb = base.add_request(_req(prompt, "ref", max_tokens=8))
    ref, _ = run_to_completion(base, [sb])

    mesh = sequence_parallel_mesh(8)
    core = EngineCore(
        cfg,
        tiny_engine(ring_prefill_threshold=64),
        seed=0,
        sp_mesh=mesh,
    )
    s = core.add_request(_req(prompt, "ring", max_tokens=8))
    got, fin = run_to_completion(core, [s])
    assert core._ring_prefills == 1, "ring path never ran"
    assert got["ring"] == ref["ref"], "ring prefill diverged from paged waves"
    assert fin["ring"] == "length"

    # Short prompts stay on the paged wave path.
    s2 = core.add_request(_req(list(range(1, 20)), "short", max_tokens=4))
    run_to_completion(core, [s2])
    assert core._ring_prefills == 1

    # Prefix-cache reuse across the two paths: repeating the long prompt
    # hits blocks the ring pass committed.
    s3 = core.add_request(_req(prompt, "again", max_tokens=8))
    d3, _ = run_to_completion(core, [s3])
    assert s3.num_cached_tokens > 0
    assert d3["again"] == ref["ref"]
