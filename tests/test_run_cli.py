"""dynamo_tpu.run single-command runner (dynamo-run parity)."""

import json
import subprocess
import sys

import pytest

pytestmark = [pytest.mark.e2e]


def test_batch_echo(tmp_path):
    inp = tmp_path / "in.jsonl"
    out = tmp_path / "out.jsonl"
    inp.write_text('{"prompt": "hello"}\n{"prompt": "there"}\n')
    proc = subprocess.run(
        [
            sys.executable, "-m", "dynamo_tpu.run",
            "--in", "batch", "--out", "echo",
            "--input", str(inp), "--output", str(out), "--max-tokens", "8",
        ],
        capture_output=True, timeout=120, text=True,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    lines = [json.loads(ln) for ln in out.read_text().splitlines()]
    assert len(lines) == 2
    # Echo engine streams the templated prompt's own bytes back.
    assert lines[0]["completion"].startswith("<|user|>")


def test_text_mocker_oneshot():
    proc = subprocess.run(
        [
            sys.executable, "-m", "dynamo_tpu.run",
            "--in", "text", "--out", "mocker",
            "--prompt", "hi", "--max-tokens", "6", "--speedup-ratio", "100",
        ],
        capture_output=True, timeout=120, text=True,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "abcdef" in proc.stdout


# ---------------------------------------------------------------------------
# --pp pre-validation (ISSUE 2 satellite): prefill buckets and model
# divisibility are checked up front with CLI-pointed errors instead of a
# late EngineCore construction failure.
# ---------------------------------------------------------------------------


def test_pp_prefill_buckets_trim_and_fallback():
    from dynamo_tpu.backends.jax.main import _pp_prefill_buckets

    # Already divisible: untouched.
    assert _pp_prefill_buckets((32, 64, 128), 2, 8) == (32, 64, 128)
    # Indivisible entries are trimmed the way dp trims decode widths.
    assert _pp_prefill_buckets((33, 64), 2, 8) == (64,)
    # Nothing survives: one synthesized bucket divisible by pp AND
    # block_size (both EngineCore checks), near the largest requested.
    assert _pp_prefill_buckets((33, 65), 2, 8) == (64,)
    for b in _pp_prefill_buckets((7,), 4, 8):
        assert b % 4 == 0 and b % 8 == 0


def test_pp_rejects_indivisible_num_layers():
    from dynamo_tpu.backends.jax.main import build_engine

    with pytest.raises(ValueError, match="num_layers"):
        build_engine("tiny", pp=3)  # tiny has 2 layers


def test_pp_rejects_indivisible_vocab(monkeypatch):
    import dataclasses

    from dynamo_tpu import engine as eng
    from dynamo_tpu.backends.jax.main import build_engine
    from dynamo_tpu.engine.config import tiny_model

    monkeypatch.setitem(
        eng.PRESETS, "tiny-oddvocab",
        lambda: dataclasses.replace(tiny_model(), num_layers=4, vocab_size=383),
    )
    with pytest.raises(ValueError, match="vocab_size"):
        build_engine("tiny-oddvocab", pp=4)
