"""dynamo_tpu.run single-command runner (dynamo-run parity)."""

import json
import subprocess
import sys

import pytest

pytestmark = [pytest.mark.e2e]


def test_batch_echo(tmp_path):
    inp = tmp_path / "in.jsonl"
    out = tmp_path / "out.jsonl"
    inp.write_text('{"prompt": "hello"}\n{"prompt": "there"}\n')
    proc = subprocess.run(
        [
            sys.executable, "-m", "dynamo_tpu.run",
            "--in", "batch", "--out", "echo",
            "--input", str(inp), "--output", str(out), "--max-tokens", "8",
        ],
        capture_output=True, timeout=120, text=True,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    lines = [json.loads(ln) for ln in out.read_text().splitlines()]
    assert len(lines) == 2
    # Echo engine streams the templated prompt's own bytes back.
    assert lines[0]["completion"].startswith("<|user|>")


def test_text_mocker_oneshot():
    proc = subprocess.run(
        [
            sys.executable, "-m", "dynamo_tpu.run",
            "--in", "text", "--out", "mocker",
            "--prompt", "hi", "--max-tokens", "6", "--speedup-ratio", "100",
        ],
        capture_output=True, timeout=120, text=True,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "abcdef" in proc.stdout
