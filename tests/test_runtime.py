"""Distributed runtime: endpoint serve/discover/stream/cancel/failure.

Parity targets: reference component model + PushRouter behaviors
(SURVEY.md §2.1) exercised through the in-process control plane.
"""

import asyncio

import pytest

from dynamo_tpu.runtime import Context, DistributedRuntime, NoInstancesError
from dynamo_tpu.runtime.store import StoreServer

pytestmark = [pytest.mark.integration, pytest.mark.pre_merge]


async def echo_handler(request, context: Context):
    for i in range(request["n"]):
        yield {"i": i, "msg": request["msg"]}


async def slow_handler(request, context: Context):
    for i in range(1000):
        if context.is_stopped:
            yield {"stopped_at": i}
            return
        yield {"i": i}
        await asyncio.sleep(0.01)


async def test_serve_and_stream():
    async with StoreServer() as server:
        worker = await DistributedRuntime.create(server.address)
        frontend = await DistributedRuntime.create(server.address)
        try:
            ep = worker.namespace("test").component("workers").endpoint("generate")
            await ep.serve(echo_handler)

            client = await frontend.namespace("test").component("workers").endpoint("generate").client()
            await client.wait_for_instances(1, timeout=5)
            stream = await client.round_robin({"n": 3, "msg": "hi"})
            out = [item async for item in stream]
            assert out == [{"i": 0, "msg": "hi"}, {"i": 1, "msg": "hi"}, {"i": 2, "msg": "hi"}]
        finally:
            await frontend.shutdown()
            await worker.shutdown()


async def test_direct_routing_and_instance_removal():
    async with StoreServer() as server:
        w1 = await DistributedRuntime.create(server.address)
        w2 = await DistributedRuntime.create(server.address)
        frontend = await DistributedRuntime.create(server.address)
        try:
            async def tagged(tag):
                async def handler(request, context):
                    yield {"worker": tag}
                return handler

            ep1 = w1.namespace("t").component("w").endpoint("gen")
            await ep1.serve(await tagged("w1"))
            ep2 = w2.namespace("t").component("w").endpoint("gen")
            await ep2.serve(await tagged("w2"))

            client = await frontend.namespace("t").component("w").endpoint("gen").client()
            ids = await client.wait_for_instances(2, timeout=5)
            assert len(ids) == 2
            assert w1.primary_lease_id in ids and w2.primary_lease_id in ids

            stream = await client.direct(w1.primary_lease_id, {})
            assert [x async for x in stream] == [{"worker": "w1"}]

            # Kill w2's process (connection drop) → instance disappears.
            await w2.shutdown()
            while len(client.instances) > 1:
                await asyncio.sleep(0.05)
            assert client.instance_ids() == [w1.primary_lease_id]
        finally:
            await frontend.shutdown()
            await w1.shutdown()


async def test_stop_generating_mid_stream():
    async with StoreServer() as server:
        worker = await DistributedRuntime.create(server.address)
        frontend = await DistributedRuntime.create(server.address)
        try:
            ep = worker.namespace("t").component("w").endpoint("slow")
            await ep.serve(slow_handler)
            client = await frontend.namespace("t").component("w").endpoint("slow").client()
            await client.wait_for_instances(1, timeout=5)

            stream = await client.round_robin({})
            got = []
            async for item in stream:
                got.append(item)
                if len(got) == 3:
                    await stream.stop()
                if "stopped_at" in item:
                    break
            assert any("stopped_at" in g for g in got)
            assert len(got) < 1000
        finally:
            await frontend.shutdown()
            await worker.shutdown()


async def test_handler_error_propagates():
    async with StoreServer() as server:
        worker = await DistributedRuntime.create(server.address)
        frontend = await DistributedRuntime.create(server.address)
        try:
            async def bad(request, context):
                yield {"ok": 1}
                raise ValueError("boom")

            ep = worker.namespace("t").component("w").endpoint("bad")
            await ep.serve(bad)
            client = await frontend.namespace("t").component("w").endpoint("bad").client()
            await client.wait_for_instances(1, timeout=5)
            stream = await client.round_robin({})
            with pytest.raises(Exception, match="boom"):
                async for _ in stream:
                    pass
        finally:
            await frontend.shutdown()
            await worker.shutdown()


async def test_no_instances_error():
    async with StoreServer() as server:
        rt = await DistributedRuntime.create(server.address)
        try:
            client = await rt.namespace("t").component("w").endpoint("none").client()
            with pytest.raises(NoInstancesError):
                await client.round_robin({})
        finally:
            await rt.shutdown()


async def test_worker_death_fails_inflight_stream():
    """A dying worker must error the client's stream, not hang it (the
    precondition for request migration)."""
    async with StoreServer() as server:
        worker = await DistributedRuntime.create(server.address)
        frontend = await DistributedRuntime.create(server.address)
        try:
            ep = worker.namespace("t").component("w").endpoint("slow")
            await ep.serve(slow_handler)
            client = await frontend.namespace("t").component("w").endpoint("slow").client()
            await client.wait_for_instances(1, timeout=5)
            stream = await client.round_robin({})
            got = 0
            with pytest.raises(ConnectionError):
                async for _ in stream:
                    got += 1
                    if got == 2:
                        await worker.shutdown()
            assert got >= 2
        finally:
            await frontend.shutdown()
