"""Sampler edge cases (ISSUE 4 satellite): top-p rank-0 survival at tiny
nucleus mass, top-k exactness at the k_cap boundary, and per-lane rng
reproducibility independent of batch neighbors — the property the
speculative verify step leans on (counter-keyed lanes must replay the
same choices whether they run as a chain, a mixed-step row, or a verify
row)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dynamo_tpu.engine.sampler import DEFAULT_TOP_CAP, sample

pytestmark = [pytest.mark.unit]


def _keys(seeds, counters):
    base = jax.random.PRNGKey(0)
    return jax.vmap(
        lambda s, c: jax.random.fold_in(jax.random.fold_in(base, s), c)
    )(jnp.asarray(seeds), jnp.asarray(counters))


def _arrs(B, temp=1.0, top_k=-1, top_p=1.0):
    return (
        jnp.full((B,), temp, jnp.float32),
        jnp.full((B,), top_k, jnp.int32),
        jnp.full((B,), top_p, jnp.float32),
    )


def test_top_p_rank0_always_kept_at_tiny_top_p():
    """top_p epsilon must still sample SOMETHING: the highest-probability
    token's preceding cumulative mass is 0 < top_p, so rank 0 survives
    the nucleus mask for any top_p > 0 — a masked-out full row would
    sample from all -inf logits and return garbage."""
    rng = np.random.RandomState(0)
    B, V = 4, 128
    logits = jnp.asarray(rng.randn(B, V).astype(np.float32) * 3)
    temp, top_k, top_p = _arrs(B, temp=0.7, top_p=1e-6)
    toks = sample(
        logits, _keys([1, 2, 3, 4], [0, 0, 0, 0]), temp, top_k, top_p
    )
    # With an epsilon nucleus only rank 0 survives -> argmax exactly.
    np.testing.assert_array_equal(
        np.asarray(toks), np.asarray(jnp.argmax(logits, axis=-1))
    )


def test_top_k_exact_at_k_cap_boundary():
    """top_k == k_cap is the last exact configuration (the docstring's
    contract: exact for k <= k_cap). Construct logits where the k_cap
    worst tokens are massively likely under a wrong implementation: only
    the top k_cap ids may ever be sampled, and k_cap-1 must exclude the
    k_cap-th ranked id."""
    B, V = 2, 256
    cap = DEFAULT_TOP_CAP
    base = np.zeros((B, V), np.float32)
    # ids 0..cap-1 are the top-cap set (descending); everything else far below.
    for i in range(cap):
        base[:, i] = 100.0 - i
    base[:, cap:] = -100.0
    logits = jnp.asarray(base)
    keys = _keys([7, 8], [0, 0])

    temp, top_k, top_p = _arrs(B, temp=5.0, top_k=cap)
    allowed = set(range(cap))
    for c in range(50):
        toks = np.asarray(
            sample(logits, _keys([7, 8], [c, c]), temp, top_k, top_p)
        )
        assert set(toks.tolist()) <= allowed

    # k = cap - 1: the cap-1 ranked id (value 100 - (cap-1)) must never
    # appear, even at high temperature.
    temp, top_k, top_p = _arrs(B, temp=5.0, top_k=cap - 1)
    seen = set()
    for c in range(100):
        toks = np.asarray(
            sample(logits, _keys([7, 8], [c, c]), temp, top_k, top_p)
        )
        seen.update(toks.tolist())
    assert cap - 1 not in seen
    assert seen <= set(range(cap - 1))


def test_per_lane_rng_independent_of_batch_neighbors():
    """A seeded lane must reproduce its choices regardless of who shares
    the batch: lane (seed=5, counter=c) draws the same token whether it
    sits in a B=1 batch, a B=4 batch of strangers, or a different lane
    index — the invariant that makes decode chains, mixed-step rows, and
    speculative verify rows interchangeable."""
    rng = np.random.RandomState(3)
    V = 96
    row = rng.randn(V).astype(np.float32)
    strangers = rng.randn(3, V).astype(np.float32)

    def draw(lane_logits_batch, seeds, counters, lane):
        temp, top_k, top_p = _arrs(len(seeds), temp=0.9, top_k=20, top_p=0.9)
        toks = sample(
            jnp.asarray(lane_logits_batch), _keys(seeds, counters),
            temp, top_k, top_p,
        )
        return int(np.asarray(toks)[lane])

    for c in range(8):
        solo = draw(row[None, :], [5], [c], 0)
        first = draw(
            np.concatenate([row[None, :], strangers]), [5, 1, 2, 3],
            [c, c + 9, c + 17, c + 31], 0,
        )
        last = draw(
            np.concatenate([strangers, row[None, :]]), [1, 2, 3, 5],
            [c + 9, c + 17, c + 31, c], 3,
        )
        assert solo == first == last
