"""TP/DP sharding on the virtual 8-device CPU mesh.

Sharded prefill+decode must compile, execute, and match the unsharded
single-device results (GSPMD inserts the collectives; numerics identical
up to reduction order). The fused wqkv/wgu projections are shard-blocked:
`init_params(rng, cfg, tp)` with different tp values describes the SAME
model with permuted fused columns, so a tp=4 run and a tp=1 run are
directly comparable.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dynamo_tpu.engine.config import EngineConfig, ModelConfig
from dynamo_tpu.engine.model import decode_tokens, init_cache, init_params
from dynamo_tpu.parallel.sharding import (
    cache_sharding,
    decode_batch_shardings,
    make_mesh,
    param_shardings,
    shard_params,
)
from tests.model_harness import prefill_chunk

CFG = ModelConfig(
    name="dryrun",
    vocab_size=512,
    hidden_size=64,
    intermediate_size=128,
    num_layers=2,
    num_heads=8,
    num_kv_heads=8,
    head_dim=16,
    dtype="float32",
    tie_embeddings=True,
)
ENG = EngineConfig(
    num_kv_blocks=32,
    block_size=8,
    max_num_seqs=8,
    max_model_len=128,
    prefill_buckets=(32, 64, 128),
    decode_buckets=(4, 8),
)


def test_mesh_construction():
    assert len(jax.devices()) == 8, "conftest must provide the 8-device CPU mesh"
    mesh = make_mesh(dp=2, tp=4)
    assert mesh.shape == {"dp": 2, "tp": 4}


def test_fused_layouts_describe_same_model():
    """init_params(tp=4) is a column permutation of init_params(tp=1):
    split_qkv recovers identical natural-order projections."""
    from dynamo_tpu.engine.model import split_gu, split_qkv

    p1 = init_params(jax.random.PRNGKey(0), CFG, tp=1)
    p4 = init_params(jax.random.PRNGKey(0), CFG, tp=4)
    x = jax.random.normal(jax.random.PRNGKey(1), (5, CFG.hidden_size))
    qkv1 = x @ p1["layers"]["wqkv"][0]
    qkv4 = x @ p4["layers"]["wqkv"][0]
    for a, b in zip(split_qkv(qkv1, CFG, 1), split_qkv(qkv4, CFG, 4)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-5)
    g1, u1 = split_gu(x @ p1["layers"]["wgu"][0], 1)
    g4, u4 = split_gu(x @ p4["layers"]["wgu"][0], 4)
    np.testing.assert_allclose(np.asarray(g1), np.asarray(g4), rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(u1), np.asarray(u4), rtol=1e-5, atol=1e-5)


@pytest.mark.slow  # heaviest tp compile; tier-1 keeps the other mesh cells
def test_sharded_prefill_decode_matches_single_device():
    prompt = list(np.random.RandomState(1).randint(1, 500, size=20))
    blocks = [0, 1, 2, 3]

    def run(params_in, cache, mesh):
        logits, cache = prefill_chunk(
            params_in, cache, prompt, 0, blocks, CFG, ENG, 32, mesh=mesh
        )
        B = 8
        tables = np.full((B, ENG.max_blocks_per_seq), ENG.garbage_block, np.int32)
        tables[0, :4] = blocks
        tok_b = jnp.zeros(B, jnp.int32).at[0].set(jnp.argmax(logits).astype(jnp.int32))
        pos = np.zeros(B, np.int32)
        pos[0] = 20
        act = np.zeros(B, bool)
        act[0] = True
        logits_b, cache = decode_tokens(
            params_in, cache, tok_b, jnp.asarray(tables),
            jnp.asarray(pos), jnp.asarray(act), CFG, ENG, mesh,
        )
        return logits, logits_b[0]

    # Single-device ground truth (tp=1 fused layout).
    params1 = init_params(jax.random.PRNGKey(0), CFG, tp=1)
    want_p, want_d = run(params1, init_cache(CFG, ENG), None)

    # Sharded: tp=4-blocked params on the mesh, cache combined-heads on tp.
    mesh = make_mesh(dp=2, tp=4)
    params4 = init_params(jax.random.PRNGKey(0), CFG, tp=4)
    sp = shard_params(params4, CFG, mesh)
    cd = jax.device_put(init_cache(CFG, ENG), cache_sharding(mesh))
    got_p, got_d = run(sp, cd, mesh)

    np.testing.assert_allclose(np.asarray(got_p), np.asarray(want_p), rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(np.asarray(got_d), np.asarray(want_d), rtol=1e-4, atol=1e-4)


def test_engine_core_on_mesh_matches_single_device():
    """The REAL EngineCore (scheduler + jitted steps + fused sampling) on a
    dp=2 x tp=2 mesh produces byte-identical greedy output."""
    from dynamo_tpu.engine.core import EngineCore
    from dynamo_tpu.llm.protocols.common import (
        PreprocessedRequest,
        SamplingOptions,
        StopConditions,
    )

    def run(mesh):
        core = EngineCore(CFG, ENG, seed=0, mesh=mesh)
        seqs = [
            core.add_request(
                PreprocessedRequest(
                    model="t",
                    token_ids=list(range(3 + i, 40 + i)),
                    request_id=f"r{i}",
                    sampling=SamplingOptions(temperature=0.0),
                    stop=StopConditions(max_tokens=5),
                )
            )
            for i in range(3)
        ]
        done: dict[str, list[int]] = {s.request_id: [] for s in seqs}
        fins: dict[str, str] = {}
        for _ in range(200):
            for seq, out in core.step():
                done[seq.request_id].extend(out.token_ids)
                if out.finish_reason:
                    fins[seq.request_id] = out.finish_reason
            if len(fins) == 3:
                break
        assert len(fins) == 3
        return done

    assert run(make_mesh(dp=2, tp=2)) == run(None)


def test_engine_core_rejects_bad_decode_bucket_for_dp():
    from dynamo_tpu.engine.core import EngineCore

    mesh = make_mesh(dp=4, tp=2)
    bad = EngineConfig(
        num_kv_blocks=32,
        block_size=8,
        max_num_seqs=8,
        max_model_len=128,
        prefill_buckets=(32,),
        decode_buckets=(6,),  # 6 % dp=4 != 0
    )
    with pytest.raises(ValueError, match="decode bucket"):
        EngineCore(CFG, bad, seed=0, mesh=mesh)


def test_param_shardings_reject_bad_tp():
    mesh = make_mesh(dp=1, tp=8)
    bad = ModelConfig(name="bad", num_kv_heads=6, num_heads=12)
    with pytest.raises(ValueError):
        param_shardings(bad, mesh)


def test_decode_batch_shardings_cover_operands():
    mesh = make_mesh(dp=4, tp=2)
    sh = decode_batch_shardings(mesh)
    assert set(sh) == {"tokens", "block_tables", "positions", "active"}


def test_int8_engine_on_mesh_matches_int8_single_device():
    """int8 weight-only params under a tp mesh (the 70B serving mode —
    placement.py fits llama3-70b-int8 on v5e-64 at tp=8 x dp=8): the
    {w, scale} dict leaves shard via expand_specs_for_params (scale
    replicates where its contraction axis collapsed to 1), and greedy
    output matches the single-device int8 engine exactly.

    The two param pytrees describe the SAME quantized model: init_params
    with different tp is a fused-column permutation, and per-output-
    channel quantization is permutation-equivariant."""
    from dynamo_tpu.engine.core import EngineCore
    from dynamo_tpu.engine.model import init_params, quantize_params
    from dynamo_tpu.llm.protocols.common import (
        PreprocessedRequest,
        SamplingOptions,
        StopConditions,
    )

    def run(params, mesh):
        core = EngineCore(CFG, ENG, params=params, seed=0, mesh=mesh)
        seqs = [
            core.add_request(
                PreprocessedRequest(
                    model="t",
                    token_ids=list(range(3 + i, 40 + i)),
                    request_id=f"r{i}",
                    sampling=SamplingOptions(temperature=0.0),
                    stop=StopConditions(max_tokens=5, ignore_eos=True),
                )
            )
            for i in range(2)
        ]
        done: dict[str, list[int]] = {s.request_id: [] for s in seqs}
        fins = 0
        for _ in range(200):
            for seq, out in core.step():
                done[seq.request_id].extend(out.token_ids)
                fins += bool(out.finish_reason)
            if fins == 2:
                return done
        raise AssertionError("never finished")

    q1 = quantize_params(init_params(jax.random.PRNGKey(0), CFG, tp=1))
    want = run(q1, None)
    q2 = quantize_params(init_params(jax.random.PRNGKey(0), CFG, tp=2))
    got = run(q2, make_mesh(dp=2, tp=2))
    assert got == want


def test_cross_tp_kv_transfer_matches_aggregated():
    """P<->D mesh mismatch: a tp=2 prefill core's held blocks imported by
    a tp=1 decode core (and the reverse direction's staging) must decode
    to exactly the aggregated output. The staged page is layout-complete
    ([L, bs, 2kv, d] gathered across shards), so the consumer's own cache
    sharding performs the relayout — the reference needs a CUDA transpose
    kernel for this (disagg_serving.md:96-98)."""
    from dynamo_tpu.engine.core import EngineCore
    from dynamo_tpu.llm.protocols.common import (
        PreprocessedRequest,
        SamplingOptions,
        StopConditions,
    )

    def req(tokens, rid, n, hold=False):
        return PreprocessedRequest(
            model="t", token_ids=list(tokens), request_id=rid,
            sampling=SamplingOptions(temperature=0.0),
            stop=StopConditions(max_tokens=n, ignore_eos=True),
            kv_transfer_params={"do_remote_decode": True} if hold else None,
        )

    def run(core, seq):
        toks = []
        for _ in range(200):
            for s, out in core.step():
                if s is seq:
                    toks.extend(out.token_ids)
            if seq.finish is not None:
                return toks
        raise AssertionError("never finished")

    prompt = list(np.random.RandomState(7).randint(1, 500, size=40))

    # Aggregated single-device ground truth (same seed = same model).
    agg = EngineCore(CFG, ENG, seed=0)
    want = run(agg, agg.add_request(req(prompt, "agg", 6)))

    # tp=2 prefill core -> tp=1 decode core over the wire protocol.
    p_core = EngineCore(CFG, ENG, seed=0, mesh=make_mesh(dp=1, tp=2))
    d_core = EngineCore(CFG, ENG, seed=0)
    tok1 = run(p_core, p_core.add_request(req(prompt, "pf", 1, hold=True)))
    descs = p_core.export_descriptors("pf")
    assert descs[0]["layout"]["tp"] == 2
    pages = p_core.read_held_pages("pf", 0, len(descs))
    n = d_core.import_blocks([dict(d, kv=kv) for d, kv in zip(descs, pages)]).imported
    p_core.release_held("pf")
    assert n == len(descs) > 0
    seq = d_core.add_request(req(prompt + tok1, "dec", 5))
    got = run(d_core, seq)
    assert tok1 + got == want
    assert seq.num_cached_tokens > 0  # rode the imported, relayouted prefix


def test_import_rejects_block_size_mismatch():
    """block_size mismatches cannot be relayouted (disjoint hash domains)
    and must fail loudly, not corrupt."""
    import dataclasses

    from dynamo_tpu.engine.core import EngineCore
    from dynamo_tpu.llm.protocols.common import (
        PreprocessedRequest,
        SamplingOptions,
        StopConditions,
    )

    prompt = list(np.random.RandomState(7).randint(1, 500, size=40))
    p_core = EngineCore(CFG, ENG, seed=0)
    pre = PreprocessedRequest(
        model="t", token_ids=prompt, request_id="pf",
        sampling=SamplingOptions(temperature=0.0),
        stop=StopConditions(max_tokens=1, ignore_eos=True),
        kv_transfer_params={"do_remote_decode": True},
    )
    seq = p_core.add_request(pre)
    for _ in range(100):
        p_core.step()
        if seq.finish is not None:
            break
    descs = p_core.export_descriptors("pf")
    pages = p_core.read_held_pages("pf", 0, len(descs))
    p_core.release_held("pf")

    d_core = EngineCore(
        CFG, dataclasses.replace(ENG, block_size=16, prefill_buckets=(32, 64, 128)),
        seed=0,
    )
    with pytest.raises(ValueError, match="block_size"):
        d_core.import_blocks([dict(d, kv=kv) for d, kv in zip(descs, pages)])
