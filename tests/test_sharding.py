"""TP/DP sharding on the virtual 8-device CPU mesh.

Sharded prefill+decode must compile, execute, and match the unsharded
single-device results (GSPMD inserts the collectives; numerics identical
up to reduction order).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dynamo_tpu.engine.config import EngineConfig, ModelConfig
from dynamo_tpu.engine.model import (
    decode_step_impl,
    init_cache,
    init_params,
    prefill_step_impl,
)
from dynamo_tpu.parallel.sharding import (
    cache_sharding,
    decode_batch_shardings,
    make_mesh,
    param_shardings,
    shard_params,
)

CFG = ModelConfig(
    name="dryrun",
    vocab_size=512,
    hidden_size=64,
    intermediate_size=128,
    num_layers=2,
    num_heads=8,
    num_kv_heads=8,
    head_dim=16,
    dtype="float32",
    tie_embeddings=True,
)
ENG = EngineConfig(
    num_kv_blocks=32,
    block_size=8,
    max_num_seqs=8,
    max_model_len=128,
    prefill_buckets=(32, 64, 128),
    decode_buckets=(4, 8),
)


def test_mesh_construction():
    assert len(jax.devices()) == 8, "conftest must provide the 8-device CPU mesh"
    mesh = make_mesh(dp=2, tp=4)
    assert mesh.shape == {"dp": 2, "tp": 4}


def test_sharded_prefill_decode_matches_single_device():
    params = init_params(jax.random.PRNGKey(0), CFG)
    prompt = list(np.random.RandomState(1).randint(1, 500, size=20))
    table = np.full(ENG.max_blocks_per_seq, ENG.garbage_block, np.int32)
    table[:4] = [0, 1, 2, 3]
    toks = np.zeros(32, np.int32)
    toks[:20] = prompt

    def run(params_in, k, v):
        logits, k, v = prefill_step_impl(
            params_in, jnp.asarray(toks), k, v, jnp.asarray(table),
            jnp.int32(20), jnp.int32(0), CFG, ENG, kv_span=32,
        )
        B = 8
        tables = np.tile(table, (B, 1))
        tok_b = jnp.zeros(B, jnp.int32).at[0].set(jnp.argmax(logits).astype(jnp.int32))
        pos = np.zeros(B, np.int32)
        pos[0] = 20
        act = np.zeros(B, bool)
        act[0] = True
        logits_b, k, v = decode_step_impl(
            params_in, tok_b, k, v, jnp.asarray(tables),
            jnp.asarray(pos), jnp.asarray(act), CFG, ENG,
        )
        return logits, logits_b[0]

    # Single-device ground truth.
    k0, v0 = init_cache(CFG, ENG)
    want_p, want_d = run(params, k0, v0)

    # Sharded: params on tp, cache kv-heads on tp, batch on dp.
    mesh = make_mesh(dp=2, tp=4)
    sp = shard_params(params, CFG, mesh)
    kd = jax.device_put(jnp.zeros_like(k0), cache_sharding(mesh))
    vd = jax.device_put(jnp.zeros_like(v0), cache_sharding(mesh))
    got_p, got_d = jax.jit(run)(sp, kd, vd)

    np.testing.assert_allclose(np.asarray(got_p), np.asarray(want_p), rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(np.asarray(got_d), np.asarray(want_d), rtol=1e-4, atol=1e-4)


def test_engine_core_on_mesh_matches_single_device():
    """The REAL EngineCore (scheduler + jitted steps + fused sampling) on a
    dp=2 x tp=2 mesh produces byte-identical greedy output."""
    from dynamo_tpu.engine.core import EngineCore
    from dynamo_tpu.llm.protocols.common import (
        PreprocessedRequest,
        SamplingOptions,
        StopConditions,
    )

    def run(mesh):
        core = EngineCore(CFG, ENG, seed=0, mesh=mesh)
        seqs = [
            core.add_request(
                PreprocessedRequest(
                    model="t",
                    token_ids=list(range(3 + i, 40 + i)),
                    request_id=f"r{i}",
                    sampling=SamplingOptions(temperature=0.0),
                    stop=StopConditions(max_tokens=5),
                )
            )
            for i in range(3)
        ]
        done: dict[str, list[int]] = {s.request_id: [] for s in seqs}
        fins: dict[str, str] = {}
        for _ in range(200):
            for seq, out in core.step():
                done[seq.request_id].extend(out.token_ids)
                if out.finish_reason:
                    fins[seq.request_id] = out.finish_reason
            if len(fins) == 3:
                break
        assert len(fins) == 3
        return done

    assert run(make_mesh(dp=2, tp=2)) == run(None)


def test_engine_core_rejects_bad_decode_bucket_for_dp():
    from dynamo_tpu.engine.core import EngineCore

    mesh = make_mesh(dp=4, tp=2)
    bad = EngineConfig(
        num_kv_blocks=32,
        block_size=8,
        max_num_seqs=8,
        max_model_len=128,
        prefill_buckets=(32,),
        decode_buckets=(6,),  # 6 % dp=4 != 0
    )
    with pytest.raises(ValueError, match="decode bucket"):
        EngineCore(CFG, bad, seed=0, mesh=mesh)


def test_param_shardings_reject_bad_tp():
    mesh = make_mesh(dp=1, tp=8)
    bad = ModelConfig(name="bad", num_kv_heads=6, num_heads=12)
    with pytest.raises(ValueError):
        param_shardings(bad, mesh)


def test_decode_batch_shardings_cover_operands():
    mesh = make_mesh(dp=4, tp=2)
    sh = decode_batch_shardings(mesh)
    assert set(sh) == {"tokens", "block_tables", "positions", "active"}
