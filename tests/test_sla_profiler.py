"""SLA profiler sweep + planner observation loop.

Parity: reference `benchmarks/profiler/profile_sla.py:52` (offline sweep
producing the planner's interpolation grids) and
`planner_core.py:180` observe_metrics (live frontend scrape driving the
adjustment loop).
"""

import asyncio
import json
import os
import subprocess
import sys

import aiohttp
import pytest

from dynamo_tpu.planner.observer import MetricsObserver, parse_prometheus
from dynamo_tpu.planner.perf_interpolation import (
    DecodeInterpolator,
    PrefillInterpolator,
    from_profile,
)
from dynamo_tpu.planner.planner_core import (
    Planner,
    PlannerConfig,
    RecordingConnector,
    SlaTargets,
)

pytestmark = [pytest.mark.integration]


def test_profiler_emits_planner_profile(tmp_path):
    """The sweep runs the REAL engine and emits exactly the dict
    from_profile() loads — closing the round-3 gap where
    perf_interpolation had no producer."""
    out = tmp_path / "profile.json"
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=os.getcwd())
    r = subprocess.run(
        [sys.executable, "benchmarks/profile_sla.py", "--preset", "tiny",
         "--quick", "--out", str(out)],
        capture_output=True, text=True, timeout=300, env=env,
    )
    assert r.returncode == 0, r.stderr[-2000:]
    profile = json.loads(out.read_text())
    assert profile["prefill"]["isl"] == [16.0, 32.0, 64.0]
    assert len(profile["prefill"]["ttft_s"]) == 3
    assert all(t > 0 for t in profile["prefill"]["ttft_s"])
    assert len(profile["decode"]["itl_s"]) == 2
    assert all(t > 0 for t in profile["decode"]["itl_s"])

    # The planner consumes it directly.
    pf, dc = from_profile(profile)
    planner = Planner(pf, dc, RecordingConnector(),
                      sla=SlaTargets(ttft_s=10.0, itl_s=10.0))
    from dynamo_tpu.planner.planner_core import Observation

    plan = planner.compute_plan(
        Observation(request_rate=1.0, mean_isl=32, mean_osl=8)
    )
    assert plan.decode_replicas >= 1 and plan.prefill_replicas >= 1


def test_parse_prometheus_sums_families():
    text = (
        "# HELP x\n"
        'dynamo_frontend_requests_total{model="a"} 3\n'
        'dynamo_frontend_requests_total{model="b"} 2\n'
        "dynamo_frontend_time_to_first_token_seconds_sum 1.5\n"
        "dynamo_frontend_time_to_first_token_seconds_count 5\n"
    )
    t = parse_prometheus(text)
    assert t["dynamo_frontend_requests_total"] == 5
    assert t["dynamo_frontend_time_to_first_token_seconds_sum"] == 1.5


@pytest.mark.e2e
async def test_planner_scales_up_under_rising_load():
    """Soak: live frontend metrics -> MetricsObserver -> Planner; a load
    ramp must raise the decode-replica recommendation (reference
    sla_planner adjustment behavior)."""
    from tests.test_e2e_frontend import Cluster

    async def fire(session, base_url, n, max_tokens=8):
        async def one(i):
            body = {
                "model": "mock",
                "messages": [{"role": "user", "content": f"load {i} " + "x" * 64}],
                "max_tokens": max_tokens,
                "temperature": 0.0,
                "stream": True,  # TTFT/ITL histograms are per-SSE-stream
            }
            async with session.post(
                f"{base_url}/v1/chat/completions", json=body
            ) as r:
                assert r.status == 200
                async for _ in r.content:
                    pass

        await asyncio.gather(*[one(i) for i in range(n)])

    # One replica sustains ~1 tok/s within the ITL SLA under this
    # synthetic profile, so a ramp to many tokens/s demands replicas.
    planner = Planner(
        PrefillInterpolator([16, 512], [0.01, 0.05]),
        DecodeInterpolator([1.0, 8.0], [0.95, 8.0]),
        RecordingConnector(),
        sla=SlaTargets(ttft_s=0.5, itl_s=1.0),
        config=PlannerConfig(predictor="constant"),
    )

    async with Cluster(num_workers=1) as c:
        obs = MetricsObserver(c.base_url)
        await obs.observe()  # baseline scrape
        async with aiohttp.ClientSession() as s:
            await fire(s, c.base_url, 1)
            await asyncio.sleep(0.5)
            o1 = await obs.observe()
            plan1 = planner.compute_plan(o1)

            await fire(s, c.base_url, 24)
            await asyncio.sleep(0.2)
            o2 = await obs.observe()
            plan2 = planner.compute_plan(o2)

    assert o2.request_rate > o1.request_rate
    assert o1.mean_osl == pytest.approx(8, abs=1)
    assert o1.observed_ttft_s is not None
    assert plan2.decode_replicas > plan1.decode_replicas, (plan1, plan2)
