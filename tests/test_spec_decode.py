"""Speculative decoding (ISSUE 4): n-gram draft + batched ragged verify.

The tentpole contract: with ``spec_decode='ngram'`` every speculating
decode row becomes a q_len<=k+1 verify row in the SAME ragged program the
schedulers already dispatch, emitting accepted+1 tokens per step — while
greedy AND seeded-temperature output stay BIT-IDENTICAL to speculation
off (verification replays the target's own per-lane counter-keyed
choices). Same parity discipline as the chunked-vs-waves suite.
"""

import asyncio
import math

import numpy as np
import pytest

from dynamo_tpu import tracing
from dynamo_tpu.engine import EngineCore, tiny_engine, tiny_model
from dynamo_tpu.llm.protocols.common import (
    PreprocessedRequest,
    SamplingOptions,
    StopConditions,
)
from dynamo_tpu.spec import SpecConfig, propose_ngram, resolve_spec_config

pytestmark = [pytest.mark.unit]

CFG = tiny_model()

# Repetitive prompts give the prompt-lookup drafter real hits, so the
# accept path (not just the all-rejected path) is exercised.
REPEAT_PROMPT = [5, 6, 7, 8] * 6
RANDOM_PROMPT = list(np.random.RandomState(0).randint(1, 200, size=40))


def _req(prompt, rid, max_tokens=16, temp=0.0, seed=None, spec=None, **stop_kw):
    return PreprocessedRequest(
        model="tiny",
        token_ids=list(prompt),
        request_id=rid,
        sampling=SamplingOptions(temperature=temp, seed=seed),
        stop=StopConditions(max_tokens=max_tokens, **stop_kw),
        spec_decode=spec,
    )


def run_to_completion(core, seqs, max_steps=2000):
    done: dict[str, list[int]] = {s.request_id: [] for s in seqs}
    finishes: dict[str, str] = {}
    for _ in range(max_steps):
        for seq, out in core.step():
            done[seq.request_id].extend(out.token_ids)
            if out.finish_reason:
                finishes[seq.request_id] = out.finish_reason
        if len(finishes) == len(seqs):
            break
    return done, finishes


# -- drafter ------------------------------------------------------------------


def test_ngram_drafter_basic_match():
    # ... 5 6 7 8 | 5 6 -> suffix [5, 6] recurs; propose [7, 8, 5]
    ctx = [5, 6, 7, 8, 5, 6, 7, 8, 5, 6]
    assert propose_ngram(ctx, 3) == [7, 8, 5]
    assert propose_ngram(ctx, 1) == [7]


def test_ngram_drafter_prefers_most_recent_occurrence():
    # Suffix [2] occurs twice; the most recent earlier one is followed
    # by 9, the older by 3.
    ctx = [1, 2, 3, 4, 2, 9, 7, 2]
    assert propose_ngram(ctx, 2, ngram_max=1) == [9, 7]


def test_ngram_drafter_no_match_and_bounds():
    assert propose_ngram([1, 2, 3, 4], 4) == []  # no repeated suffix
    assert propose_ngram([], 4) == []
    assert propose_ngram([1], 4) == []
    assert propose_ngram([1, 1], 0) == []
    # Window excludes the distant match.
    ctx = [7, 8, 9] + [1, 2, 3, 4] * 5 + [7, 8]
    assert propose_ngram(ctx, 2, window=8) == []


def test_ngram_drafter_longest_suffix_wins():
    # 3-gram [1, 2, 3] matches (-> 9); the 1-gram [3] alone would pick 5.
    ctx = [1, 2, 3, 9, 3, 5, 1, 2, 3]
    assert propose_ngram(ctx, 1, ngram_max=3) == [9]


# -- config resolution --------------------------------------------------------


def test_spec_config_resolution():
    default = SpecConfig(k=4)
    assert resolve_spec_config(default, None, 4) is default
    assert resolve_spec_config(default, {"method": "off"}, 4) is None
    assert resolve_spec_config(None, None, 4) is None
    # Request enables speculation on an engine whose default is off.
    got = resolve_spec_config(None, {"method": "ngram", "k": 2}, 4)
    assert got is not None and got.k == 2
    # Per-request k clamps to the engine's static width.
    got = resolve_spec_config(default, {"k": 99}, 4)
    assert got.k == 4
    # The host-CPU knobs clamp to the engine baseline too: an unclamped
    # ngram_max/window would let one request inject O(window x ngram)
    # drafter work into every engine step.
    got = resolve_spec_config(default, {"ngram_max": 8192, "window": 10**9}, 4)
    assert got.ngram_max == default.ngram_max
    assert got.window == default.window
    with pytest.raises(ValueError, match="method"):
        resolve_spec_config(default, {"method": "medusa"}, 4)


def test_engine_spec_config_validation():
    with pytest.raises(ValueError, match="spec_decode"):
        EngineCore(CFG, tiny_engine(spec_decode="medusa"), seed=0)
    with pytest.raises(ValueError, match="spec_k"):
        EngineCore(CFG, tiny_engine(spec_decode="ngram", spec_k=0), seed=0)


# -- greedy parity ------------------------------------------------------------


def _run_all(engine_kw, reqs):
    core = EngineCore(CFG, tiny_engine(**engine_kw), seed=0)
    seqs = [core.add_request(r) for r in reqs]
    done, fin = run_to_completion(core, seqs)
    return core, done, fin


def test_greedy_parity_spec_on_vs_off_waves():
    reqs = lambda: [  # noqa: E731
        _req(REPEAT_PROMPT, "rep", max_tokens=20, ignore_eos=True),
        _req(RANDOM_PROMPT, "rnd", max_tokens=12),
        _req([9, 9, 9, 9, 9, 9], "nines", max_tokens=16, ignore_eos=True),
    ]
    _, base, fb = _run_all({}, reqs())
    core, spec, fs = _run_all({"spec_decode": "ngram", "spec_k": 4}, reqs())
    assert base == spec
    assert fb == fs
    st = core.spec_decode_stats()
    assert st["verify_steps"] > 0
    assert st["acceptance_rate"] > 0  # repetitive greedy output drafts land


def test_greedy_parity_spec_in_chunked_mixed_step():
    """The acceptance-criterion case: speculating decodes ride a chunked
    MIXED step (verify rows next to a long prompt's prefill chunks) and
    still match the non-speculative stream token for token."""
    long_prompt = list(np.random.RandomState(1).randint(1, 200, size=200))

    def run(spec_on):
        kw = dict(scheduling="chunked", prefill_chunk=32)
        if spec_on:
            kw.update(spec_decode="ngram", spec_k=4)
        core = EngineCore(CFG, tiny_engine(**kw), seed=0)
        d1 = core.add_request(
            _req(REPEAT_PROMPT, "d1", max_tokens=40, ignore_eos=True)
        )
        d2 = core.add_request(
            _req([3, 4] * 8, "d2", max_tokens=40, ignore_eos=True)
        )
        while not (d1.prefill_done and d2.prefill_done):
            core.step()
        seqs = [d1, d2, core.add_request(_req(long_prompt, "long", max_tokens=6))]
        done, fin = run_to_completion(core, seqs)
        return core, done, fin

    core_off, done_off, fin_off = run(False)
    core_on, done_on, fin_on = run(True)
    assert done_off == done_on
    assert fin_off == fin_on
    assert core_on.spec_stats.verify_steps > 0
    assert core_on.sched_stats["mixed_steps"] > 0


def test_seeded_temperature_parity_spec_on_vs_off():
    """Verification replays the target's counter-keyed sampler, so even
    TEMPERATURE lanes are bit-identical with speculation on — a stronger
    guarantee than lossy rejection sampling."""
    reqs = lambda: [  # noqa: E731
        _req(REPEAT_PROMPT, "a", max_tokens=20, temp=0.8, seed=42,
             ignore_eos=True),
        _req(RANDOM_PROMPT, "b", max_tokens=14, temp=1.2, seed=7),
    ]
    _, base, _ = _run_all({}, reqs())
    _, spec, _ = _run_all({"spec_decode": "ngram", "spec_k": 4}, reqs())
    assert base == spec


def test_parity_with_logprobs():
    def run(spec_on):
        kw = {"spec_decode": "ngram", "spec_k": 4} if spec_on else {}
        core = EngineCore(CFG, tiny_engine(**kw), seed=0)
        pre = _req(REPEAT_PROMPT, "lp", max_tokens=10, ignore_eos=True)
        pre.output.logprobs = 3
        seq = core.add_request(pre)
        toks, entries = [], []
        for _ in range(200):
            for s, out in core.step():
                toks.extend(out.token_ids)
                entries.extend(out.logprobs or [])
                if out.finish_reason:
                    return toks, entries
        raise AssertionError("did not finish")

    t0, e0 = run(False)
    t1, e1 = run(True)
    assert t0 == t1
    assert len(e1) == len(t1)
    assert [e["token_id"] for e in e0] == [e["token_id"] for e in e1]
    for a, b in zip(e0, e1):
        assert a["top"] == b["top"]
        assert abs(a["logprob"] - b["logprob"]) < 1e-5


# -- scheduling / budget ------------------------------------------------------


def test_draft_tokens_count_against_token_budget():
    """Chunked mixed steps: drafted tokens consume max_num_batched_tokens
    (with a one-block reserve so prefill admission can't starve)."""
    budget = 16
    core = EngineCore(
        CFG,
        tiny_engine(
            scheduling="chunked", prefill_chunk=8,
            max_num_batched_tokens=budget, prefill_buckets=(16, 32, 64),
            spec_decode="ngram", spec_k=4,
        ),
        seed=0,
    )
    decoders = [
        core.add_request(
            _req([5, 6] * 6, f"d{i}", max_tokens=40, ignore_eos=True)
        )
        for i in range(3)
    ]
    while not all(s.prefill_done for s in decoders):
        core.step()
    long = core.add_request(
        _req(list(range(1, 65)), "long", max_tokens=2, ignore_eos=True)
    )
    while not long.prefill_done:
        core.step()
        assert core.sched_stats["last_step_batched_tokens"] <= budget
        # The one-block reserve kept prefill moving: the long prompt
        # always gets a chunk while decodes speculate.
    run_to_completion(core, decoders + [long])


def test_many_spec_lanes_never_exceed_budget():
    """The overflow regression: with more speculating lanes than the
    draft budget covers, every lane's BASE token is pre-charged, so the
    step total stays under max_num_batched_tokens (no bucket overflow,
    no prefill starvation) and every lane keeps emitting."""
    budget = 16
    core = EngineCore(
        CFG,
        tiny_engine(
            scheduling="chunked", prefill_chunk=8,
            max_num_batched_tokens=budget, prefill_buckets=(16, 32, 64),
            decode_buckets=(4, 8), max_num_seqs=8,
            spec_decode="ngram", spec_k=4,
        ),
        seed=0,
    )
    lanes = [
        core.add_request(
            _req([5, 6] * 6, f"d{i}", max_tokens=30, ignore_eos=True)
        )
        for i in range(7)
    ]
    while not all(s.prefill_done for s in lanes):
        core.step()
    long = core.add_request(
        _req(list(range(1, 49)), "long", max_tokens=2, ignore_eos=True)
    )
    steps_to_prefill = 0
    while not long.prefill_done:
        outs = core.step()
        steps_to_prefill += 1
        assert core.sched_stats["last_step_batched_tokens"] <= budget
        assert steps_to_prefill < 50, "prefill starved by speculation"
        # In-flight lanes keep emitting every mixed step. (Under the
        # universal megastep a fused step emits up to k tokens per lane,
        # so the whole cohort can finish while the long prompt still
        # chunks — the guard only applies while lanes remain.)
        emitted_ids = {s.request_id for s, _ in outs}
        live_lanes = [s for s in lanes if s.finish is None]
        if live_lanes:
            assert any(s.request_id in emitted_ids for s in live_lanes)
    run_to_completion(core, lanes + [long])


def test_spec_respects_max_tokens_budget():
    """Drafting never overshoots the generation budget, and the stream
    ends with exactly max_tokens tokens."""
    core = EngineCore(
        CFG, tiny_engine(spec_decode="ngram", spec_k=4), seed=0
    )
    seq = core.add_request(
        _req(REPEAT_PROMPT, "m", max_tokens=7, ignore_eos=True)
    )
    done, fin = run_to_completion(core, [seq])
    assert len(done["m"]) == 7
    assert fin["m"] == "length"


def test_spec_under_block_pressure_preempts_and_recovers():
    """Verify rows grow blocks like decode rows; under pressure the
    engine preempts/degrades but the allocator lands back at baseline
    and output parity holds."""
    def run(spec_on):
        kw = dict(num_kv_blocks=12, max_model_len=64)
        if spec_on:
            kw.update(spec_decode="ngram", spec_k=4)
        core = EngineCore(CFG, tiny_engine(**kw), seed=0)
        seqs = [
            core.add_request(
                _req([5, 6] * 8, "a", max_tokens=24, ignore_eos=True)
            ),
            core.add_request(
                _req([7, 8] * 8, "b", max_tokens=24, ignore_eos=True)
            ),
        ]
        done, fin = run_to_completion(core, seqs, max_steps=4000)
        assert core.allocator.used_blocks == len(core.allocator._inactive)
        assert core.allocator._partials == 0
        return done, fin

    base = run(False)
    spec = run(True)
    assert base == spec


# -- per-request plumbing -----------------------------------------------------


def test_per_request_spec_override():
    # Engine default OFF, request turns speculation ON.
    core = EngineCore(CFG, tiny_engine(), seed=0)
    on = core.add_request(
        _req(REPEAT_PROMPT, "on", spec={"method": "ngram", "k": 3})
    )
    off = core.add_request(_req(REPEAT_PROMPT, "off"))
    assert on.spec is not None and on.spec.k == 3
    assert off.spec is None
    done, _ = run_to_completion(core, [on, off])
    assert done["on"] == done["off"]  # parity inside ONE mixed batch
    assert core.spec_stats.verify_rows > 0

    # Engine default ON, request turns it off.
    core2 = EngineCore(
        CFG, tiny_engine(spec_decode="ngram", spec_k=4), seed=0
    )
    seq = core2.add_request(_req(REPEAT_PROMPT, "x", spec={"method": "off"}))
    assert seq.spec is None
    # k clamps to the engine's static width.
    seq2 = core2.add_request(_req(REPEAT_PROMPT, "y", spec={"k": 99}))
    assert seq2.spec.k == 4
    with pytest.raises(ValueError, match="method"):
        core2.add_request(_req(REPEAT_PROMPT, "z", spec={"method": "eagle"}))


def test_spec_decode_rides_openai_dyn_to_wire():
    """dyn.spec_decode -> preprocessor -> PreprocessedRequest -> wire dict
    -> from_wire: the field the router used to drop now round-trips to
    the worker payload."""
    from dynamo_tpu.llm.model_card import ModelDeploymentCard
    from dynamo_tpu.llm.preprocessor import OpenAIPreprocessor
    from dynamo_tpu.llm.protocols.openai import ChatCompletionRequest

    body = ChatCompletionRequest.model_validate(
        {
            "model": "tiny",
            "messages": [{"role": "user", "content": "hello"}],
            "dyn": {"spec_decode": {"method": "ngram", "k": 2}},
        }
    )
    mdc = ModelDeploymentCard(
        name="tiny", tokenizer="byte", model_type="chat", context_length=256
    )
    pre = OpenAIPreprocessor(mdc).preprocess_chat(body)
    assert pre.spec_decode == {"method": "ngram", "k": 2}
    wire = pre.to_wire()
    assert wire["spec_decode"] == {"method": "ngram", "k": 2}
    back = PreprocessedRequest.from_wire(wire)
    assert back.spec_decode == {"method": "ngram", "k": 2}
    # Unset stays None end to end.
    body2 = ChatCompletionRequest.model_validate(
        {"model": "tiny", "messages": [{"role": "user", "content": "hi"}]}
    )
    pre2 = OpenAIPreprocessor(mdc).preprocess_chat(body2)
    assert pre2.spec_decode is None
    assert PreprocessedRequest.from_wire(pre2.to_wire()).spec_decode is None


# -- observability ------------------------------------------------------------


def test_spec_spans_and_metrics():
    tracing.configure(enabled=True, sample=1.0)
    collector = tracing.get_collector()
    collector.clear()
    core = EngineCore(
        CFG, tiny_engine(spec_decode="ngram", spec_k=4), seed=0
    )
    seq = core.add_request(
        _req(REPEAT_PROMPT, "t", max_tokens=16, ignore_eos=True)
    )
    run_to_completion(core, [seq])
    stats = collector.stats()
    drafts = [s for s in stats if s.name == "spec_draft"]
    verifies = [s for s in stats if s.name == "spec_verify"]
    assert drafts and verifies
    assert sum(v.attrs["accepted"] for v in verifies) == (
        core.spec_stats.accepted_tokens
    )
    assert all("drafted" in v.attrs for v in verifies)

    fpm = core.metrics()
    assert fpm.spec_decode is not None
    assert fpm.spec_decode["enabled"] == 1
    assert fpm.spec_decode["acceptance_rate"] > 0
    assert fpm.spec_decode["mean_accepted_len"] >= 1.0
    # Round-trips the (previously dead) ForwardPassMetrics field.
    from dynamo_tpu.llm.kv_router.protocols import ForwardPassMetrics

    back = ForwardPassMetrics.from_wire(fpm.to_wire())
    assert back.spec_decode == fpm.spec_decode

    # Speculation off and unused: field stays None (wire compat).
    core_off = EngineCore(CFG, tiny_engine(), seed=0)
    assert core_off.metrics().spec_decode is None


def test_spec_gauges_exported():
    from dynamo_tpu.runtime.metrics import MetricsRegistry
    from dynamo_tpu.runtime.status_server import (
        SPEC_GAUGES,
        SystemStatusServer,
        bind_spec_gauges,
    )

    core = EngineCore(
        CFG, tiny_engine(spec_decode="ngram", spec_k=4), seed=0
    )
    seq = core.add_request(
        _req(REPEAT_PROMPT, "g", max_tokens=12, ignore_eos=True)
    )
    run_to_completion(core, [seq])
    status = SystemStatusServer(MetricsRegistry())
    bind_spec_gauges(status, core.spec_decode_stats)
    text = status.metrics.render().decode() if isinstance(
        status.metrics.render(), bytes
    ) else status.metrics.render()
    for _, (name, _doc) in SPEC_GAUGES.items():
        assert name in text
    assert "spec_decode_enabled" in text
    # The scrape-time closure reads live stats.
    st = core.spec_decode_stats()
    assert st["acceptance_rate"] > 0


# -- mocker: acceptance-rate simulation ---------------------------------------


def _mock_engine(spec_rate=None, **kw):
    from dynamo_tpu.llm.mocker.engine import MockEngineArgs, MockTpuEngine

    args = MockEngineArgs(
        num_kv_blocks=512, block_size=4, max_num_batched_tokens=256,
        **(
            dict(spec_decode="ngram", spec_k=4, spec_acceptance_rate=spec_rate)
            if spec_rate is not None
            else {}
        ),
        **kw,
    )
    return MockTpuEngine(args)


def _mock_seq(prompt, rid, max_tokens, block_size, spec_k=0):
    from dynamo_tpu.llm.mocker.engine import _Seq
    from dynamo_tpu.tokens import TokenBlockSequence, compute_seq_hashes

    s = _Seq(
        request_id=rid,
        prompt=prompt,
        max_tokens=max_tokens,
        out=asyncio.Queue(),
        seq=TokenBlockSequence(prompt, block_size),
        prompt_hashes=compute_seq_hashes(prompt, block_size),
        stop=StopConditions(max_tokens=max_tokens, ignore_eos=True),
    )
    s.spec_k = spec_k
    return s


def _drain_mock(eng, seq):
    from dynamo_tpu.llm.mocker.engine import MockTpuEngine

    toks, iters = [], 0
    eng._waiting.append(seq)
    eng._admit()
    while seq in eng._running:
        eng._step()
        iters += 1
        while not seq.out.empty():
            item = seq.out.get_nowait()
            if item is not MockTpuEngine._FINISHED:
                toks.extend(item.get("token_ids", []))
    return toks, iters


def test_mocker_spec_stream_bit_identical_and_fewer_iterations():
    base = _mock_engine()
    t0, i0 = _drain_mock(base, _mock_seq([1] * 8, "a", 30, 4))
    spec = _mock_engine(spec_rate=0.7)
    t1, i1 = _drain_mock(spec, _mock_seq([1] * 8, "a", 30, 4, spec_k=4))
    assert t0 == t1
    assert i1 < i0
    st = spec.spec_decode_stats()
    assert st["acceptance_rate"] > 0
    assert st["verify_steps"] > 0
    assert spec.metrics().spec_decode is not None


def test_mocker_spec_tpot_ab_on_virtual_clock():
    """The acceptance-criterion A/B: at acceptance >= 0.5, decode TPOT on
    the mocker's virtual clock improves vs speculation off (one dispatch
    amortizes over accepted+1 tokens; draft tokens are priced like
    prefill tokens, so the win is net of verify cost)."""

    def tpot(spec_rate):
        from dynamo_tpu.llm.mocker.engine import MockEngineArgs, MockTpuEngine

        args = MockEngineArgs(
            num_kv_blocks=8192, block_size=32, max_num_seqs=32,
            max_num_batched_tokens=2048, enable_prefix_caching=False,
            **(
                dict(
                    spec_decode="ngram", spec_k=4,
                    spec_acceptance_rate=spec_rate,
                )
                if spec_rate is not None
                else {}
            ),
        )
        eng = MockTpuEngine(args)
        seqs = [
            _mock_seq(
                [1 + (j % 7)] * 128, f"s{j}", 64, 32,
                spec_k=4 if spec_rate is not None else 0,
            )
            for j in range(16)
        ]
        for s in seqs:
            eng._waiting.append(s)
        vt = 0.0
        first: dict[str, float] = {}
        gaps: list[float] = []
        prev: dict[str, float] = {}
        counts: dict[str, int] = {}
        while any(s in eng._running or s in eng._waiting for s in seqs):
            eng._admit()
            p, d = eng._step()
            vt += (
                args.base_iter_us
                + p * args.prefill_us_per_token
                + d * args.decode_us_per_seq
            ) / 1e6
            for s in seqs:
                while not s.out.empty():
                    item = s.out.get_nowait()
                    if not isinstance(item, dict):
                        continue
                    n = len(item.get("token_ids", []))
                    if not n:
                        continue
                    rid = s.request_id
                    if rid in first:
                        # n tokens landed this step: n TPOT samples over
                        # the gap (chunked emission still yields honest
                        # per-token pacing).
                        gaps.extend([(vt - prev[rid]) / n] * n)
                    first.setdefault(rid, vt)
                    prev[rid] = vt
                    counts[rid] = counts.get(rid, 0) + n
        gaps.sort()
        return (
            gaps[len(gaps) // 2],
            gaps[min(len(gaps) - 1, int(0.99 * len(gaps)))],
            vt,
        )

    off_p50, off_p99, off_total = tpot(None)
    on_p50, on_p99, on_total = tpot(0.6)
    # Headline: median TPOT and total decode wall-clock both improve.
    assert on_p50 < off_p50, (on_p50, off_p50)
    assert on_total < off_total, (on_total, off_total)
    # Tail: a first-draft rejection pays the k verify forwards for one
    # emitted token, so p99 trades a BOUNDED amount (the per-token cost
    # of a miss step is base + k*prefill_us over 1 token).
    assert on_p99 < off_p99 * 1.6, (on_p99, off_p99)
    # Below-threshold acceptance must not catastrophically regress: the
    # verify cost is bounded by k draft-token forwards per step.
    low_p50, _, low_total = tpot(0.2)
    assert low_p50 < off_p50 * 1.5
    assert low_total < off_total * 1.5


def test_mocker_per_request_spec_override():
    """The mocker honors PreprocessedRequest.spec_decode, so frontend /
    router e2e tests can exercise per-request speculation CPU-only."""
    from dynamo_tpu.llm.mocker.engine import MockEngineArgs, MockTpuEngine
    from dynamo_tpu.runtime.engine import Context

    async def run():
        eng = MockTpuEngine(
            MockEngineArgs(
                num_kv_blocks=256, block_size=4, speedup_ratio=100.0,
            )
        )
        pre = _req([1] * 8, "r1", max_tokens=12, ignore_eos=True,
                   spec={"method": "ngram", "k": 3})
        toks = []
        async for out in eng.generate(pre.to_wire(), Context("r1")):
            toks.extend(out.get("token_ids", []))
        assert eng.spec_stats.verify_rows > 0
        assert len(toks) == 12
        if eng._loop_task is not None:
            eng._loop_task.cancel()
        return toks

    toks = asyncio.get_event_loop_policy().new_event_loop().run_until_complete(run())
    assert toks == [97 + (i % 26) for i in range(12)]


# -- on-device drafting (ISSUE 18) --------------------------------------------


def test_device_matcher_replays_host_drafter_exactly():
    """The replay-exactness contract: over randomized contexts, windows,
    suffix bounds, vocab sizes and budgets, ``device_ngram_draft``
    proposes exactly what ``propose_ngram`` would from the same tail —
    or nothing. This is what makes the device drafter's hit-rate stats
    mean the same thing the host drafter's would (bit-identity of the
    STREAM never depended on it; the replay sampler guarantees that)."""
    import jax.numpy as jnp

    from dynamo_tpu.engine.sampler import device_ngram_draft

    rng = np.random.RandomState(1234)
    for _ in range(60):
        window = int(rng.randint(4, 25))
        nmax = int(rng.randint(1, 4))
        vocab = int(rng.choice([3, 5, 50]))
        k = int(rng.randint(1, 6))
        H = window + nmax
        L = int(rng.randint(0, H + 1))
        ctx = [int(t) for t in rng.randint(0, vocab, size=L)]
        want = propose_ngram(ctx, k, ngram_max=nmax, window=window)
        hist = np.full((1, H), -1, np.int32)
        if L:
            hist[0, H - L:] = ctx
        draft, dlen = device_ngram_draft(
            jnp.asarray(hist), jnp.asarray([L], jnp.int32),
            jnp.asarray([window], jnp.int32),
            jnp.asarray([1], jnp.int32), jnp.asarray([nmax], jnp.int32),
            jnp.asarray([k], jnp.int32),
            ngram_max_static=nmax, slots=k,
        )
        got = [int(t) for t in np.asarray(draft)[0][: int(dlen[0])]]
        assert got == want, (ctx, window, nmax, k, got, want)


def test_device_draft_parity_matrix():
    """Bit-identity of the device-drafted stream vs host-drafted spec vs
    speculation OFF, across scheduler shapes — greedy and seeded
    temperature lanes, an EOS-able lane, waves / chunked+async / block
    pressure (where the dd reservation can't be met and lanes degrade to
    host-drafted verify rows). The drafter placement must never move the
    stream; only the stats may differ."""
    reqs = lambda: [  # noqa: E731
        _req(REPEAT_PROMPT, "rep", max_tokens=20, ignore_eos=True),
        _req(RANDOM_PROMPT, "rnd", max_tokens=12),
        _req(REPEAT_PROMPT, "tmp", max_tokens=16, temp=0.9, seed=42,
             ignore_eos=True),
    ]
    matrix = [
        dict(megastep_k=4),
        dict(megastep_k=4, scheduling="chunked", prefill_chunk=32,
             async_exec=True),
        dict(megastep_k=4, num_kv_blocks=28, max_model_len=64),
    ]
    for shape in matrix:
        _, base, fb = _run_all(dict(shape), reqs())
        _, host, fh = _run_all(
            dict(shape, spec_decode="ngram", spec_k=4), reqs()
        )
        core, dev, fd = _run_all(
            dict(shape, spec_decode="ngram", spec_k=4,
                 spec_device_draft=True),
            reqs(),
        )
        assert base == host == dev, shape
        assert fb == fh == fd, shape
        if "num_kv_blocks" not in shape:
            st = core.spec_decode_stats()
            assert st["device_rounds"] > 0, shape
            assert st["device_hits"] > 0, shape


def test_device_draft_amortizes_dispatches():
    """The perf mechanism, pinned structurally: at equal spec_k the
    device drafter runs multiple draft->verify->accept rounds per
    dispatch, so dispatches-per-accepted-token drops vs host drafting."""
    reqs = lambda: [  # noqa: E731
        _req(REPEAT_PROMPT, "rep", max_tokens=24, ignore_eos=True),
    ]
    host, _, _ = _run_all(
        dict(megastep_k=8, spec_decode="ngram", spec_k=4), reqs()
    )
    dev, _, _ = _run_all(
        dict(megastep_k=8, spec_decode="ngram", spec_k=4,
             spec_device_draft=True),
        reqs(),
    )
    sh = host.spec_decode_stats()
    sd = dev.spec_decode_stats()
    assert sd["device_rounds"] > 0
    assert (
        sd["dispatches_per_accepted_token"]
        < sh["dispatches_per_accepted_token"]
    ), (sd, sh)


def test_mocker_device_draft_parity_and_amortization():
    """Mocker mirror: the device-drafted stream is bit-identical to the
    host-drafted and spec-off streams, in fewer dispatches, with device
    rounds priced on the virtual clock (DYN_SPEC_DRAFT_ROUND_US)."""
    def run(spec_rate, device):
        kw = dict(megastep_k=4)
        if spec_rate is not None:
            kw.update(spec_device_draft=device)
        eng = _mock_engine(spec_rate=spec_rate, **kw)
        seq = _mock_seq([1] * 8, "a", 30, 4,
                        spec_k=4 if spec_rate is not None else 0)
        if spec_rate is not None:
            seq.spec_device = device
        toks, iters = _drain_mock(eng, seq)
        return eng, toks, iters

    _, t_base, i_base = run(None, False)
    _, t_host, i_host = run(0.9, False)
    eng, t_dev, i_dev = run(0.9, True)
    assert t_base == t_host == t_dev
    assert i_dev <= i_host <= i_base
    st = eng.spec_decode_stats()
    assert st["device_rounds"] > 0
    assert st["device_hits"] > 0
    assert st["dispatches_per_accepted_token"] > 0
