"""System status server: health transitions, liveness, prometheus text."""

import aiohttp
import pytest

from dynamo_tpu.runtime.status_server import SystemStatusServer

pytestmark = [pytest.mark.unit]


async def test_status_server_lifecycle():
    srv = SystemStatusServer(host="127.0.0.1", port=0)
    await srv.start()
    base = f"http://127.0.0.1:{srv.port}"
    try:
        async with aiohttp.ClientSession() as s:
            async with s.get(f"{base}/health") as r:
                assert r.status == 503  # no endpoints yet -> starting
                body = await r.json()
                assert body["status"] == "starting"

            srv.set_endpoint_health("/dynamo/backend/generate", True)
            async with s.get(f"{base}/health") as r:
                assert r.status == 200
                body = await r.json()
                assert body["status"] == "healthy"
                assert body["endpoints"]["/dynamo/backend/generate"] == "ready"

            srv.set_endpoint_health("/dynamo/backend/generate", False)
            async with s.get(f"{base}/health") as r:
                assert r.status == 503

            async with s.get(f"{base}/live") as r:
                assert (await r.json())["status"] == "live"

            async with s.get(f"{base}/metrics") as r:
                text = await r.text()
                assert "system_uptime_seconds" in text
    finally:
        await srv.stop()
