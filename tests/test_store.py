"""Control-plane store: KV/lease/watch/pubsub/queue semantics.

Parity with the reference's reliance on etcd+NATS behavior (SURVEY.md §1 L0):
lease expiry removes keys and notifies watchers; prefix watches see initial
state + live events; queues block on pop; pub/sub matches NATS-style.
"""

import asyncio

import pytest

from dynamo_tpu.runtime.store import StoreClient, StoreServer
from dynamo_tpu.runtime.store.server import subject_matches

pytestmark = [pytest.mark.integration, pytest.mark.pre_merge]


async def test_kv_roundtrip():
    async with StoreServer() as server:
        async with await StoreClient.open(server.address) as c:
            await c.kv_put("/a/b", b"1")
            await c.kv_put("/a/c", b"2")
            assert await c.kv_get("/a/b") == b"1"
            assert await c.kv_get("/missing") is None
            assert await c.kv_get_prefix("/a/") == {"/a/b": b"1", "/a/c": b"2"}
            assert await c.kv_del("/a/b") == 1
            assert await c.kv_get("/a/b") is None


async def test_create_only_conflict():
    async with StoreServer() as server:
        async with await StoreClient.open(server.address) as c:
            await c.kv_put("/x", b"1", create_only=True)
            with pytest.raises(Exception, match="exists"):
                await c.kv_put("/x", b"2", create_only=True)


async def test_watch_sees_initial_and_live_events():
    async with StoreServer() as server:
        async with await StoreClient.open(server.address) as c:
            await c.kv_put("/models/a", b"A")
            watch = await c.kv_watch("/models/")
            ev = StoreClient.as_watch_event(await watch.get(timeout=2))
            assert (ev.type, ev.key, ev.value) == ("put", "/models/a", b"A")
            await c.kv_put("/models/b", b"B")
            ev = StoreClient.as_watch_event(await watch.get(timeout=2))
            assert (ev.type, ev.key) == ("put", "/models/b")
            await c.kv_del("/models/a")
            ev = StoreClient.as_watch_event(await watch.get(timeout=2))
            assert (ev.type, ev.key) == ("delete", "/models/a")


async def test_lease_keys_vanish_on_connection_drop():
    async with StoreServer() as server:
        watcher = await StoreClient.open(server.address)
        watch = await watcher.kv_watch("/instances/")
        worker = await StoreClient.open(server.address)
        lease = await worker.lease_grant(ttl=30.0)
        await worker.kv_put("/instances/w1", b"addr", lease=lease)
        ev = StoreClient.as_watch_event(await watch.get(timeout=2))
        assert (ev.type, ev.key) == ("put", "/instances/w1")
        # Simulate worker death: drop the connection without revoking.
        await worker.close()
        ev = StoreClient.as_watch_event(await watch.get(timeout=2))
        assert (ev.type, ev.key) == ("delete", "/instances/w1")
        assert await watcher.kv_get("/instances/w1") is None
        await watcher.close()


async def test_lease_revoke_deletes_keys():
    async with StoreServer() as server:
        async with await StoreClient.open(server.address) as c:
            lease = await c.lease_grant(ttl=30.0)
            await c.kv_put("/i/x", b"1", lease=lease)
            await c.lease_revoke(lease)
            assert await c.kv_get("/i/x") is None


async def test_pubsub_wildcards():
    async with StoreServer() as server:
        async with await StoreClient.open(server.address) as c:
            sub = await c.subscribe("kv_events.>")
            assert await c.publish("kv_events.worker1", b"e1") == 1
            assert await c.publish("other.worker1", b"nope") == 0
            msg = StoreClient.as_message(await sub.get(timeout=2))
            assert (msg.subject, msg.payload) == ("kv_events.worker1", b"e1")


async def test_queue_blocking_pop():
    async with StoreServer() as server:
        async with await StoreClient.open(server.address) as c1:
            async with await StoreClient.open(server.address) as c2:
                pop = asyncio.create_task(c1.queue_pop("prefill", timeout=5.0))
                await asyncio.sleep(0.05)
                await c2.queue_push("prefill", b"req1")
                assert await pop == b"req1"
                assert await c1.queue_pop("empty", timeout=0.0) is None


async def test_object_store():
    async with StoreServer() as server:
        async with await StoreClient.open(server.address) as c:
            await c.obj_put("mdc", "llama", b"card")
            assert await c.obj_get("mdc", "llama") == b"card"
            assert await c.obj_list("mdc") == ["llama"]
            assert await c.obj_del("mdc", "llama")
            assert await c.obj_get("mdc", "llama") is None


def test_subject_matching():
    assert subject_matches("a.b", "a.b")
    assert not subject_matches("a.b", "a.c")
    assert subject_matches("a.*", "a.b")
    assert not subject_matches("a.*", "a.b.c")
    assert subject_matches("a.>", "a.b.c.d")
    assert not subject_matches("a.>", "a")


@pytest.mark.integration
async def test_client_survives_store_restart():
    """Store restart: the client reconnects with backoff, re-attaches its
    lease under the SAME id (worker identity embeds it), replays
    lease-bound registrations, and resumes subscriptions + watches
    (VERDICT r3 weak #9 — the reference gets this from etcd/NATS client
    libraries; this store's client owns it)."""
    import asyncio

    server = StoreServer()
    await server.start()
    port = server.port
    client = await StoreClient.open(server.address)
    try:
        lease = await client.lease_grant(ttl=5.0)
        await client.kv_put("/reg/instance-1", b"worker-payload", lease=lease)
        sub = await client.subscribe("events")
        watch = await client.kv_watch("/reg/", with_initial=False)

        await server.stop()
        await asyncio.sleep(0.3)
        # Same address, empty state — as after a crash+restart.
        server2 = StoreServer(port=port)
        await server2.start()
        try:
            # Wait for the session to rebuild.
            for _ in range(100):
                try:
                    if await client.kv_get("/reg/instance-1") == b"worker-payload":
                        break
                except ConnectionError:
                    pass
                await asyncio.sleep(0.1)
            # Lease-bound registration replayed under the same lease id.
            assert await client.kv_get("/reg/instance-1") == b"worker-payload"

            # Old subscription object resumes delivery.
            pub = await StoreClient.open(server2.address)
            try:
                await pub.publish("events", b"hello-again")
                msg = await sub.get(timeout=5)
                assert msg["p"] == b"hello-again"

                # Watch resumed too (replayed with initial state, then live).
                await pub.kv_put("/reg/instance-2", b"x")
                saw = []
                for _ in range(10):
                    ev = await watch.get(timeout=5)
                    saw.append(StoreClient.as_watch_event(ev).key)
                    if "/reg/instance-2" in saw:
                        break
                assert "/reg/instance-2" in saw
            finally:
                await pub.close()

            # The replayed lease still expires if the client dies: revoke
            # and confirm the registration vanishes.
            await client.lease_revoke(lease)
            assert await client.kv_get("/reg/instance-1") is None
        finally:
            await server2.stop()
    finally:
        await client.close()


# -- reconnect backoff jitter (ISSUE 4 satellite) -----------------------------


def test_reconnect_delay_full_jitter_bounds():
    """Full jitter: every delay lands in [0, min(0.2 * 2**attempt, 2.0)]
    and the ceiling caps at 2.0 from attempt 4 on."""
    import random

    from dynamo_tpu.runtime.store.client import (
        RECONNECT_BASE_S,
        RECONNECT_CAP_S,
        RECONNECT_FACTOR,
        reconnect_delay,
    )

    rng = random.Random(7)
    for attempt in range(12):
        ceiling = min(
            RECONNECT_BASE_S * RECONNECT_FACTOR ** attempt, RECONNECT_CAP_S
        )
        for _ in range(200):
            d = reconnect_delay(attempt, rng)
            assert 0.0 <= d <= ceiling, (attempt, d, ceiling)
    assert RECONNECT_BASE_S * RECONNECT_FACTOR ** 4 > RECONNECT_CAP_S


def test_reconnect_delay_decorrelates_clients():
    """Two clients that disconnect at the same instant must not redial in
    lockstep: with jitter the per-attempt delays differ (this is the
    thundering-herd property the deterministic 0.2 -> x2 schedule lacked)."""
    import random

    from dynamo_tpu.runtime.store.client import reconnect_delay

    a = [reconnect_delay(i, random.Random(1)) for i in range(8)]
    b = [reconnect_delay(i, random.Random(2)) for i in range(8)]
    assert a != b
