"""Token block hashing semantics (parity: reference tokens.rs test surface)."""

import pytest

from dynamo_tpu.tokens import (
    TokenBlockSequence,
    compute_block_hash,
    compute_seq_hashes,
    tokens_to_blocks,
)

pytestmark = [pytest.mark.unit, pytest.mark.pre_merge]


def test_block_hash_deterministic():
    a = compute_block_hash([1, 2, 3, 4])
    b = compute_block_hash([1, 2, 3, 4])
    assert a == b
    assert a != compute_block_hash([1, 2, 3, 5])


def test_chain_differs_by_parent():
    h = compute_block_hash([1, 2, 3, 4])
    child_of_root = compute_block_hash([5, 6, 7, 8])
    child_of_h = compute_block_hash([5, 6, 7, 8], parent_hash=h)
    assert child_of_root != child_of_h


def test_seq_hashes_ignore_partial_tail():
    full = compute_seq_hashes(list(range(8)), block_size=4)
    with_tail = compute_seq_hashes(list(range(10)), block_size=4)
    assert len(full) == 2
    assert with_tail == full


def test_shared_prefix_shares_hashes():
    a = compute_seq_hashes([1, 2, 3, 4, 5, 6, 7, 8, 9], block_size=4)
    b = compute_seq_hashes([1, 2, 3, 4, 9, 9, 9, 9], block_size=4)
    assert a[0] == b[0]
    assert a[1] != b[1]


def test_incremental_matches_bulk():
    tokens = list(range(100, 177))
    seq = TokenBlockSequence(block_size=16)
    for t in tokens:
        seq.append(t)
    assert seq.block_hashes == compute_seq_hashes(tokens, 16)
    assert seq.all_tokens() == tokens
    assert len(seq) == len(tokens)
    assert len(seq.partial_tokens) == 77 % 16


def test_extend_returns_completed_blocks():
    seq = TokenBlockSequence(block_size=4)
    done = seq.extend(range(11))
    assert [b.position for b in done] == [0, 1]
    assert done[1].parent_hash == done[0].block_hash


def test_truncate_replays_chain():
    tokens = list(range(40))
    seq = TokenBlockSequence(tokens, block_size=8)
    seq.truncate(20)
    assert seq.all_tokens() == tokens[:20]
    assert seq.block_hashes == compute_seq_hashes(tokens[:20], 8)


def test_tokens_to_blocks():
    blocks, partial = tokens_to_blocks(list(range(10)), 4)
    assert len(blocks) == 2
    assert partial == [8, 9]
    assert blocks[0].tokens == (0, 1, 2, 3)
