"""Distributed request tracing (dynamo_tpu/tracing): span model, ring
buffer, W3C propagation, the disabled-tracer no-op bound, and the e2e
stitched waterfall over the mocker-backed frontend.

Acceptance (ISSUE 2): one request through the full stack yields a single
trace containing at least {http, tokenize, route, prefill, decode} spans
with monotonic, non-overlapping phase timestamps; with tracing disabled
the same path records zero spans and a span call costs < 1 µs.
"""

from __future__ import annotations

import asyncio
import time

import aiohttp
import pytest

from dynamo_tpu import tracing
from dynamo_tpu.runtime.logging_setup import (
    TRACEPARENT_HEADER,
    make_traceparent,
    parse_traceparent,
)

pytestmark = [pytest.mark.pre_merge]


@pytest.fixture(autouse=True)
def clean_tracing():
    """Tracing state is process-global: pin config and drain the buffer
    around every test so cluster tests elsewhere can't bleed spans in."""
    tracing.configure(enabled=True, sample=1.0, buffer=4096)
    tracing.get_collector().clear()
    tracing.get_collector()._metrics.clear()
    yield
    tracing.configure(enabled=True, sample=1.0, buffer=4096)
    tracing.get_collector().clear()
    tracing.get_collector()._metrics.clear()


# ---------------------------------------------------------------------------
# Span model + collector
# ---------------------------------------------------------------------------


def test_span_context_manager_records_duration_and_attrs():
    tracer = tracing.get_tracer("unit")
    with tracer.span("phase", attrs={"k": 1}) as s:
        s.set("tokens", 7)
        time.sleep(0.001)
    spans = tracing.get_collector().spans()
    assert len(spans) == 1
    (rec,) = spans
    assert rec.name == "phase" and rec.service == "unit"
    assert rec.attrs == {"k": 1, "tokens": 7}
    assert rec.end_s > rec.start_s
    assert len(rec.trace_id) == 32 and len(rec.span_id) == 16
    assert rec.parent_id is None  # root


def test_span_finish_is_idempotent_and_exception_sets_error():
    tracer = tracing.get_tracer("unit")
    with pytest.raises(RuntimeError):
        with tracer.span("boom") as s:
            raise RuntimeError("x")
    s.finish()  # double-finish must not double-record
    spans = tracing.get_collector().spans()
    assert len(spans) == 1
    assert spans[0].attrs["error"] == "RuntimeError"


def test_explicit_parent_links_build_one_trace():
    tracer = tracing.get_tracer("unit")
    with tracer.span("root") as root:
        with tracer.span("child", parent=root) as child:
            pass
    spans = {s.name: s for s in tracing.get_collector().spans()}
    assert spans["child"].trace_id == spans["root"].trace_id
    assert spans["child"].parent_id == spans["root"].span_id


def test_ring_buffer_evicts_oldest():
    tracing.configure(buffer=8)
    tracer = tracing.get_tracer("unit")
    for i in range(20):
        with tracer.span(f"s{i}"):
            pass
    collector = tracing.get_collector()
    assert len(collector) == collector.capacity == 8
    assert [s.name for s in collector.spans()] == [f"s{i}" for i in range(12, 20)]


def test_record_files_retroactive_phase():
    tracer = tracing.get_tracer("unit")
    t0 = time.time() - 0.5
    tracer.record("prefill", t0, t0 + 0.25, attrs={"tokens": 128})
    (rec,) = tracing.get_collector().spans()
    assert rec.start_s == t0
    assert abs(rec.duration_s - 0.25) < 1e-9


def test_traces_payload_groups_and_waterfalls():
    tracer = tracing.get_tracer("unit")
    with tracer.span("http") as root:
        with tracer.span("tokenize", parent=root):
            pass
        with tracer.span("decode", parent=root):
            pass
    with tracer.span("other"):
        pass
    collector = tracing.get_collector()
    payloads = collector.traces(limit=10)
    assert len(payloads) == 2
    assert payloads[0]["trace_id"] != payloads[1]["trace_id"]
    pinned = collector.traces(trace_id=root.trace_id)
    assert len(pinned) == 1
    phases = [w["phase"] for w in pinned[0]["waterfall"]]
    assert phases == ["http", "tokenize", "decode"]
    for w in pinned[0]["waterfall"]:
        assert w["offset_ms"] >= 0.0
    assert tracing.phase_order(pinned[0]["spans"]) == phases


# ---------------------------------------------------------------------------
# Propagation + sampling
# ---------------------------------------------------------------------------


def test_header_roundtrip_stitches_across_processes():
    """inject_headers → extract over the dataplane header map produces
    child spans in the same trace with correct parent links."""
    frontend = tracing.get_tracer("frontend")
    engine = tracing.get_tracer("engine")
    with frontend.span("http") as root:
        headers = {"x-request-id": "r-1"}
        tracing.inject_headers(root, headers)
        assert parse_traceparent(headers[TRACEPARENT_HEADER]) == (
            root.trace_id,
            root.span_id,
        )
        # "Other process": only the headers cross the wire.
        with engine.span("prefill", headers=headers) as child:
            pass
    assert child.trace_id == root.trace_id
    assert child.parent_id == root.span_id
    assert tracing.extract_context({}) is None
    assert tracing.extract_context({"traceparent": "garbage"}) is None


def test_noop_span_leaves_headers_untouched():
    tracing.configure(enabled=False)
    headers = {"x-request-id": "r-1"}
    tracing.inject_headers(tracing.NOOP_SPAN, headers)
    assert TRACEPARENT_HEADER not in headers


def test_sampling_is_deterministic_on_trace_id():
    """Every process keeps or drops the SAME traces: a span created from
    a sampled-out parent context must also be dropped, with no
    coordination beyond the trace id itself."""
    tracing.configure(sample=0.5)
    a = tracing.get_tracer("svc-a")
    b = tracing.get_tracer("svc-b")
    kept = dropped = 0
    for _ in range(200):
        root = a.span("root")
        if root.recording:
            kept += 1
            headers = tracing.inject_headers(root, {})
            child = b.span("child", headers=headers)
            assert child.recording, "child of a kept trace must be kept"
            child.finish()
            root.finish()
        else:
            dropped += 1
            # A sampled-out root propagates nothing; a child built from a
            # made-up context with the same (unsampled) id also drops.
    assert kept and dropped, f"0.5 sampling degenerate: kept={kept}"
    tracing.configure(sample=0.0)
    assert not a.span("x").recording
    tracing.configure(sample=1.0)


def test_sampled_out_parent_drops_children_too():
    """A NOOP parent (sampled-out trace) must propagate the drop — a
    child span minting a fresh trace would orphan-pollute /traces."""
    tracer = tracing.get_tracer("unit")
    tracing.configure(sample=0.0)
    root = tracer.span("http")
    tracing.configure(sample=1.0)  # children would now sample in...
    child = tracer.span("tokenize", parent=root)
    assert child is tracing.NOOP_SPAN  # ...but inherit the parent's drop
    tracer.record("route", time.time() - 0.1, time.time(), parent=root)
    child.finish()
    root.finish()
    assert len(tracing.get_collector()) == 0


def test_stat_spans_stay_out_of_traces_but_feed_histograms():
    """High-frequency step spans (stat=True) must not evict request spans
    from the trace ring or show up as one-span traces in /traces."""
    from dynamo_tpu.runtime.metrics import MetricsRegistry

    registry = MetricsRegistry()
    collector = tracing.get_collector()
    collector.bind_metrics(registry)
    tracer = tracing.get_tracer("engine")
    with tracer.span("prefill"):
        pass
    t0 = time.time()
    for _ in range(50):
        tracer.record("engine_decode_step", t0, t0 + 0.001, stat=True)
    assert len(collector) == 1  # request ring untouched
    assert len(collector.stats()) == 50
    assert len(collector.traces(limit=100)) == 1  # no step-span "traces"
    text = registry.render().decode()
    assert 'phase="engine_decode_step"' in text  # histograms still fed
    collector.clear()
    assert not collector.stats()


def test_bound_registries_are_held_weakly():
    """A dead service's registry must unbind itself — bind_metrics has no
    explicit unbind, so liveness rides the weakref."""
    import gc

    from dynamo_tpu.runtime.metrics import MetricsRegistry

    collector = tracing.get_collector()
    registry = MetricsRegistry()
    collector.bind_metrics(registry)
    assert len(collector._metrics) == 1
    del registry
    gc.collect()
    tracer = tracing.get_tracer("unit")
    tracer.record("phase", time.time() - 0.01, time.time())  # prunes dead refs
    assert collector._metrics == []


# ---------------------------------------------------------------------------
# Disabled tracer: hard no-op, micro-benched
# ---------------------------------------------------------------------------


def test_disabled_tracer_records_nothing():
    tracing.configure(enabled=False)
    tracer = tracing.get_tracer("unit")
    with tracer.span("phase") as s:
        s.set("k", 1)
    tracer.record("phase", time.time() - 1, time.time())
    assert s is tracing.NOOP_SPAN
    assert s.context is None
    assert len(tracing.get_collector()) == 0
    assert not tracing.trace_enabled()


def test_noop_span_call_is_under_one_microsecond():
    """Acceptance bound: a disabled tracer's span() is one attribute
    check + one return. Best-of-5 over 20k calls to shrug off CI noise."""
    tracing.configure(enabled=False)
    tracer = tracing.get_tracer("bench")
    n = 20_000
    best = float("inf")
    for _ in range(5):
        t0 = time.perf_counter()
        for _ in range(n):
            # dynalint: allow-unclosed-span(disabled-tracer bench: span() returns the shared NOOP_SPAN)
            tracer.span("phase")
        best = min(best, (time.perf_counter() - t0) / n)
    assert best < 1e-6, f"no-op span call took {best * 1e9:.0f} ns"


# ---------------------------------------------------------------------------
# Metrics + planner feed
# ---------------------------------------------------------------------------


def test_bound_registry_gets_per_phase_histograms():
    from dynamo_tpu.planner.observer import parse_prometheus
    from dynamo_tpu.runtime.metrics import MetricsRegistry

    registry = MetricsRegistry()
    collector = tracing.get_collector()
    collector.bind_metrics(registry)
    collector.bind_metrics(registry)  # idempotent
    tracer = tracing.get_tracer("engine")
    t0 = time.time()
    tracer.record("prefill", t0 - 0.2, t0 - 0.1)
    tracer.record("prefill", t0 - 0.1, t0)
    tracer.record("decode", t0 - 0.1, t0)
    text = registry.render().decode()
    assert 'phase="prefill"' in text and 'phase="decode"' in text
    totals = parse_prometheus(text)
    base = "dynamo_trace_phase_duration_seconds"
    assert totals[f"{base}_count{{prefill}}"] == 2
    assert abs(totals[f"{base}_sum{{prefill}}"] - 0.2) < 1e-6
    assert totals[f"{base}_count{{decode}}"] == 1


async def test_observer_decomposes_ttft_by_phase():
    from dynamo_tpu.planner.observer import MetricsObserver, parse_prometheus

    def scrape_text(reqs, prefill_sum, prefill_n, route_sum, route_n):
        return "\n".join([
            f"dynamo_frontend_requests_total {reqs}",
            'dynamo_trace_phase_duration_seconds_sum{service="engine",phase="prefill"} '
            + str(prefill_sum),
            'dynamo_trace_phase_duration_seconds_count{service="engine",phase="prefill"} '
            + str(prefill_n),
            'dynamo_trace_phase_duration_seconds_sum{phase="route",service="router"} '
            + str(route_sum),
            'dynamo_trace_phase_duration_seconds_count{phase="route",service="router"} '
            + str(route_n),
        ])

    windows = [
        scrape_text(10, 1.0, 10, 0.05, 10),
        scrape_text(30, 5.0, 30, 0.25, 30),
    ]

    obs = MetricsObserver("http://unused")

    async def fake_scrape():
        return parse_prometheus(windows.pop(0))

    obs._scrape = fake_scrape
    first = await obs.observe()
    assert first.phase_means is None  # no previous window yet
    second = await obs.observe()
    # Window delta: prefill (5.0-1.0)/(30-10)=0.2s, route 0.01s.
    assert abs(second.phase_means["prefill"] - 0.2) < 1e-9
    assert abs(second.phase_means["route"] - 0.01) < 1e-9


def test_planner_prefers_measured_prefill_phase_over_total_ttft():
    from dynamo_tpu.planner.planner_core import Observation, Planner, RecordingConnector

    class PrefillInterp:
        def ttft_at(self, isl):
            return 0.1

        def max_isl_within(self, s):
            return 4096.0

        def throughput_at(self, isl):
            return 10_000.0

    class DecodeInterp:
        def max_concurrency_within(self, s):
            return 8.0

        def itl_at(self, c):
            return 0.01

        def throughput_at(self, c):
            return 10_000.0

    def plan_with(obs):
        p = Planner(PrefillInterp(), DecodeInterp(), RecordingConnector())
        p._update_corrections(obs)
        return p.correction_prefill

    # Totals say TTFT is 4x the profile — but the tracer shows prefill
    # itself is on-profile (the regression is upstream: route/queue).
    decomposed = Observation(
        request_rate=1.0, mean_isl=256.0, mean_osl=64.0,
        observed_ttft_s=0.4, phase_means={"prefill": 0.1, "route": 0.28},
    )
    totals_only = Observation(
        request_rate=1.0, mean_isl=256.0, mean_osl=64.0, observed_ttft_s=0.4,
    )
    assert plan_with(decomposed) == pytest.approx(1.0)
    assert plan_with(totals_only) == pytest.approx(4.0)


# ---------------------------------------------------------------------------
# Frontend satellites: client x-request-id adoption
# ---------------------------------------------------------------------------


def test_inbound_request_id_sanitized_and_length_capped():
    from types import SimpleNamespace

    from dynamo_tpu.llm.http_service import HttpService

    class Req:
        def __init__(self, headers):
            self.headers = headers

    svc = SimpleNamespace(_inflight_rids=set())

    def rid_for(headers):
        return HttpService._request_id(svc, Req(headers), "chat")

    assert rid_for({"x-request-id": "client-abc.123:7"}) == "client-abc.123:7"
    # Malformed / oversized / missing values get a freshly minted id.
    for bad in ("", "x" * 129, "sp ace", "new\nline", "emoji-⚡", "a;b"):
        minted = rid_for({"x-request-id": bad})
        assert minted != bad and minted.startswith("chat-")
    # A duplicate id while the first request is still in flight gets a
    # fresh mint (engine queues / KV pulls are keyed by request id);
    # after release the client id is adoptable again.
    dup = rid_for({"x-request-id": "client-abc.123:7"})
    assert dup != "client-abc.123:7" and dup.startswith("chat-")
    HttpService._release_request_id(svc, "client-abc.123:7")
    assert rid_for({"x-request-id": "client-abc.123:7"}) == "client-abc.123:7"


# ---------------------------------------------------------------------------
# Migration: one request id / trace id across replayed attempts
# ---------------------------------------------------------------------------


async def test_migrated_stream_keeps_one_trace_across_attempts():
    from dynamo_tpu.llm.migration import Migration
    from dynamo_tpu.llm.protocols.common import (
        LLMEngineOutput,
        PreprocessedRequest,
        SamplingOptions,
        StopConditions,
    )

    class FlakyClient:
        """First worker dies mid-stream; the retry lands on worker 2."""

        def pick_instance(self, mode, exclude):
            return 2 if 1 in exclude else 1

        async def direct(self, worker_id, payload, headers=None):
            async def stream():
                yield LLMEngineOutput(token_ids=[100]).to_wire()
                if worker_id == 1:
                    raise ConnectionError("conn reset")
                yield LLMEngineOutput(token_ids=[101], finish_reason="stop").to_wire()

            return stream()

    parent = make_traceparent()
    trace_id = parse_traceparent(parent)[0]
    m = Migration(client=FlakyClient(), push_router=None, mode="round_robin", limit=2)
    pre = PreprocessedRequest(
        model="t", token_ids=[1, 2, 3], request_id="req-1",
        sampling=SamplingOptions(), stop=StopConditions(max_tokens=8),
    )
    out = [
        o async for o in m.generate(pre, headers={TRACEPARENT_HEADER: parent})
    ]
    assert [t for o in out for t in o.token_ids] == [100, 100, 101]

    attempts = [
        s for s in tracing.get_collector().spans() if s.name == "migration_attempt"
    ]
    assert [s.attrs["outcome"] for s in attempts] == ["failed", "completed"]
    # ONE request id and ONE trace id across the replayed attempt.
    assert {s.attrs["request_id"] for s in attempts} == {"req-1"}
    assert {s.trace_id for s in attempts} == {trace_id}
    assert attempts[1].attrs["attempt"] == 1
    assert attempts[1].attrs["replayed_tokens"] == 1  # token 100 replayed


async def test_unmigrated_stream_records_no_attempt_spans():
    from dynamo_tpu.llm.migration import Migration
    from dynamo_tpu.llm.protocols.common import (
        LLMEngineOutput,
        PreprocessedRequest,
        SamplingOptions,
        StopConditions,
    )

    class HealthyClient:
        def pick_instance(self, mode, exclude):
            return 1

        async def direct(self, worker_id, payload, headers=None):
            async def stream():
                yield LLMEngineOutput(token_ids=[7], finish_reason="stop").to_wire()

            return stream()

    m = Migration(client=HealthyClient(), push_router=None, mode="round_robin")
    pre = PreprocessedRequest(
        model="t", token_ids=[1], request_id="req-2",
        sampling=SamplingOptions(), stop=StopConditions(max_tokens=4),
    )
    assert [o async for o in m.generate(pre)]
    names = [s.name for s in tracing.get_collector().spans()]
    assert "migration_attempt" not in names  # fast path stays span-free


# ---------------------------------------------------------------------------
# E2E: mocker-backed frontend → /traces stitched waterfall
# ---------------------------------------------------------------------------

REQUIRED_PHASES = ("http", "tokenize", "route", "prefill", "decode")


async def _one_chat(base_url: str, rid: str | None = None) -> dict:
    body = {
        "model": "mock",
        "messages": [{"role": "user", "content": "trace this request end to end"}],
        "max_tokens": 8,
        "stream": False,
    }
    headers = {"x-request-id": rid} if rid else {}
    async with aiohttp.ClientSession() as s:
        async with s.post(
            f"{base_url}/v1/chat/completions", json=body, headers=headers
        ) as resp:
            assert resp.status == 200, await resp.text()
            return await resp.json()


@pytest.mark.e2e
async def test_e2e_traces_endpoint_serves_stitched_waterfall():
    from tests.test_e2e_frontend import Cluster

    async with Cluster(num_workers=1) as cluster:
        tracing.get_collector().clear()
        resp = await _one_chat(cluster.base_url, rid="client-rid-1")
        assert resp["id"] == "client-rid-1"  # inbound x-request-id honored

        # The engine-side spans are filed in the stream's finally block,
        # which can land a beat after the HTTP response — poll briefly.
        target = None
        for _ in range(40):
            async with aiohttp.ClientSession() as s:
                async with s.get(f"{cluster.base_url}/traces?limit=50") as r:
                    assert r.status == 200
                    payload = await r.json()
            assert payload["enabled"] is True
            for trace in payload["traces"]:
                spans = {sp["name"]: sp for sp in trace["spans"]}
                if (
                    spans.get("http", {}).get("attrs", {}).get("request_id")
                    == "client-rid-1"
                    and all(p in spans for p in REQUIRED_PHASES)
                ):
                    target = trace
                    break
            if target:
                break
            await asyncio.sleep(0.05)
        assert target is not None, f"no stitched trace for request: {payload}"

        spans = {sp["name"]: sp for sp in target["spans"]}
        for phase in REQUIRED_PHASES:
            assert phase in spans, f"missing {phase!r}: {sorted(spans)}"
        # One stitched trace: every phase shares the root's trace id, and
        # the cross-process phases parent back to the frontend root.
        assert {sp["trace_id"] for sp in spans.values()} == {target["trace_id"]}
        root = spans["http"]
        assert root["parent_id"] is None
        for phase in ("tokenize", "route", "prefill", "decode"):
            assert spans[phase]["parent_id"] == root["span_id"], phase

        # Monotonic, non-overlapping phase sequence inside the root.
        seq = [spans[p] for p in ("tokenize", "route", "prefill", "decode")]
        for prev, cur in zip(seq, seq[1:]):
            assert cur["start_s"] >= prev["end_s"] - 1e-6, (
                f"{cur['name']} overlaps {prev['name']}"
            )
            assert cur["end_s"] >= cur["start_s"]
        assert root["start_s"] <= seq[0]["start_s"]
        assert root["end_s"] >= seq[-1]["end_s"] - 1e-6
        assert spans["decode"]["attrs"]["tokens"] >= 1
        assert spans["prefill"]["attrs"]["prompt_tokens"] >= 1

        # The waterfall view mirrors span order with root-relative offsets.
        phases_in_waterfall = [w["phase"] for w in target["waterfall"]]
        assert phases_in_waterfall[0] == "http"
        assert all(w["offset_ms"] >= 0 for w in target["waterfall"])

        # Disabled tracer: the SAME path records zero spans.
        tracing.configure(enabled=False)
        try:
            tracing.get_collector().clear()
            await _one_chat(cluster.base_url)
            async with aiohttp.ClientSession() as s:
                async with s.get(f"{cluster.base_url}/traces") as r:
                    off = await r.json()
            assert off["enabled"] is False
            assert off["buffered_spans"] == 0 and off["traces"] == []
        finally:
            tracing.configure(enabled=True)
