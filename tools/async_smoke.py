"""Async-execution smoke: a mocker-backed frontend with ``--async-exec on``
streams BIT-IDENTICAL output to a twin deployment with it off, and the
worker's trace collector carries the ``host_gap`` stat the pipelined loop
reports per dispatch.

This is the user-visible contract of the async pipelined execution loop
(ISSUE 5): one-step-ahead scheduling and device-resident token feedback
change WHEN work happens — per-dispatch host overhead hides under device
compute — never which tokens are emitted. The same greedy request runs
against an async-on deployment and an async-off deployment (fresh store +
worker + frontend each, so no state leaks between the two), and the full
streamed text must match byte for byte.

CI usage (`.github/workflows/ci.yml` async-smoke step) and local:

    python tools/async_smoke.py
"""

from __future__ import annotations

import asyncio
import sys
from pathlib import Path

# Runnable straight from a checkout (CI also pip-installs the package).
sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


async def stream_text(session, url: str, body: dict) -> str:
    """POST a streaming chat completion; return the concatenated content."""
    import json

    parts: list[str] = []
    async with session.post(url, json=body) as resp:
        assert resp.status == 200, await resp.text()
        async for raw in resp.content:
            line = raw.decode("utf-8", "replace").strip()
            if not line.startswith("data:") or "[DONE]" in line:
                continue
            chunk = json.loads(line[len("data:"):])
            for choice in chunk.get("choices", []):
                parts.append((choice.get("delta") or {}).get("content") or "")
    return "".join(parts)


async def run_one(async_exec: bool) -> tuple[str, int]:
    """Boot store + mocker (async on/off) + frontend, stream one greedy
    request, and return (streamed text, host_gap stat-span count)."""
    import aiohttp

    from dynamo_tpu import tracing
    from dynamo_tpu.backends.mocker import run_mocker
    from dynamo_tpu.frontend.main import run_frontend
    from dynamo_tpu.llm.mocker import MockEngineArgs
    from dynamo_tpu.runtime import DistributedRuntime
    from dynamo_tpu.runtime.store import StoreServer

    tracing.configure(enabled=True, sample=1.0)
    collector = tracing.get_collector()
    collector.clear()

    store = StoreServer()
    await store.start()
    worker_rt = await DistributedRuntime.create(store.address)
    served = asyncio.Event()
    worker = asyncio.create_task(
        run_mocker(
            worker_rt,
            model_name="mock",
            engine_args=MockEngineArgs(
                num_kv_blocks=8192,
                block_size=8,
                async_exec=async_exec,
                speedup_ratio=50.0,
            ),
            served_event=served,
        )
    )
    await asyncio.wait_for(served.wait(), 30)
    front_rt = await DistributedRuntime.create(store.address)
    ready = asyncio.Event()
    services: list = []
    frontend = asyncio.create_task(
        run_frontend(
            front_rt, http_host="127.0.0.1", http_port=0,
            router_mode="kv", ready_event=ready, service_out=services,
        )
    )
    await asyncio.wait_for(ready.wait(), 30)
    base = f"http://127.0.0.1:{services[0].port}"

    async with aiohttp.ClientSession() as s:
        for _ in range(200):
            async with s.get(f"{base}/v1/models") as r:
                if (await r.json())["data"]:
                    break
            await asyncio.sleep(0.05)
        else:
            raise TimeoutError("model never appeared on frontend")

        text = await stream_text(
            s, f"{base}/v1/chat/completions",
            {
                "model": "mock",
                "messages": [{"role": "user", "content": "async smoke test"}],
                "max_tokens": 32,
                "temperature": 0,
                "stream": True,
            },
        )

    gaps = [sp for sp in collector.stats() if sp.name == "host_gap"]
    if async_exec:
        assert gaps, "host_gap stat missing from the async-on worker"
        assert any(sp.attrs.get("overlapped") for sp in gaps), (
            "async-on worker never reported an overlapped dispatch gap"
        )

    for task in (worker, frontend):
        task.cancel()
    for rt in (worker_rt, front_rt):
        await rt.shutdown()
    await store.stop()
    return text, len(gaps)


async def run() -> None:
    text_on, gaps_on = await run_one(True)
    text_off, _ = await run_one(False)
    assert text_on, "async-on deployment streamed nothing"
    assert text_on == text_off, (
        f"async-on stream diverged from async-off:\n  on : {text_on!r}\n"
        f"  off: {text_off!r}"
    )
    print(
        f"async-smoke OK: {len(text_on)} chars bit-identical async-on vs "
        f"off; {gaps_on} host_gap stats recorded", flush=True,
    )


def main() -> int:
    asyncio.run(run())
    return 0


if __name__ == "__main__":
    sys.exit(main())
