"""Chaos smoke: kill one of two mocker workers mid-stream and assert the
client sees ONE uninterrupted, bit-exact stream.

The end-to-end containment contract of the failure-containment layer
(ISSUE 6): a mocker-backed frontend with two workers streams a greedy
request; one worker's runtime is shut down after the first few tokens;
request migration replays the accumulated tokens on the survivor and the
client-visible stream must be byte-identical to a no-fault run against a
single worker. The smoke also asserts the observability surface is
populated: a ``migration_attempt`` span in the trace collector and a
recorded failure against the dead worker's address in the egress pool's
breaker stats.

CI usage (`.github/workflows/ci.yml` chaos-smoke step) and local:

    python tools/chaos_smoke.py
"""

from __future__ import annotations

import asyncio
import sys
from pathlib import Path

# Runnable straight from a checkout (CI also pip-installs the package).
sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


async def stream_text(session, url: str, body: dict, on_chunk=None) -> str:
    """POST a streaming chat completion; return the concatenated content,
    calling ``on_chunk(parts)`` after every content delta."""
    import json

    parts: list[str] = []
    async with session.post(url, json=body) as resp:
        assert resp.status == 200, await resp.text()
        async for raw in resp.content:
            line = raw.decode("utf-8", "replace").strip()
            if not line.startswith("data:") or "[DONE]" in line:
                continue
            chunk = json.loads(line[len("data:"):])
            for choice in chunk.get("choices", []):
                piece = (choice.get("delta") or {}).get("content") or ""
                if piece:
                    parts.append(piece)
                    if on_chunk is not None:
                        await on_chunk(parts)
    return "".join(parts)


async def boot_worker(store_address: str, args) -> tuple:
    from dynamo_tpu.backends.mocker import run_mocker
    from dynamo_tpu.runtime import DistributedRuntime

    rt = await DistributedRuntime.create(store_address)
    served = asyncio.Event()
    task = asyncio.create_task(
        run_mocker(rt, model_name="mock", engine_args=args, served_event=served)
    )
    await asyncio.wait_for(served.wait(), 30)
    return rt, task


async def run_cluster(num_workers: int, kill_mid_stream: bool) -> str:
    """Boot store + N mocker workers + frontend; stream one greedy
    request, optionally shutting one worker down mid-stream; return the
    streamed text."""
    import aiohttp

    from dynamo_tpu import tracing
    from dynamo_tpu.frontend.main import run_frontend
    from dynamo_tpu.llm.mocker import MockEngineArgs
    from dynamo_tpu.runtime import DistributedRuntime
    from dynamo_tpu.runtime.store import StoreServer

    tracing.configure(enabled=True, sample=1.0)
    collector = tracing.get_collector()
    collector.clear()

    # ~20ms per decode iteration so the kill lands mid-stream.
    args = MockEngineArgs(
        num_kv_blocks=2048, block_size=8, decode_us_per_seq=20000.0
    )
    store = StoreServer()
    await store.start()
    workers = [await boot_worker(store.address, args) for _ in range(num_workers)]
    front_rt = await DistributedRuntime.create(store.address)
    # A tight stall deadline doubles as the wedged-worker detector.
    front_rt.egress.policy.stall_s = 5.0
    ready = asyncio.Event()
    services: list = []
    frontend = asyncio.create_task(
        run_frontend(
            front_rt, http_host="127.0.0.1", http_port=0,
            router_mode="kv", ready_event=ready, service_out=services,
        )
    )
    await asyncio.wait_for(ready.wait(), 30)
    base = f"http://127.0.0.1:{services[0].port}"

    killed = asyncio.Event()

    async def maybe_kill(parts: list[str]) -> None:
        if kill_mid_stream and not killed.is_set() and len(parts) >= 3:
            killed.set()
            rt, task = workers[0]
            task.cancel()
            await rt.shutdown()  # worker 0 dies with the stream in flight

    async with aiohttp.ClientSession() as s:
        for _ in range(200):
            async with s.get(f"{base}/v1/models") as r:
                if (await r.json())["data"]:
                    break
            await asyncio.sleep(0.05)
        else:
            raise TimeoutError("model never appeared on frontend")

        text = await stream_text(
            s, f"{base}/v1/chat/completions",
            {
                "model": "mock",
                "messages": [{"role": "user", "content": "chaos smoke test"}],
                "max_tokens": 16,
                "temperature": 0,
                "stream": True,
            },
            on_chunk=maybe_kill,
        )

    if kill_mid_stream:
        assert killed.is_set(), "stream finished before the kill landed"
        attempts = [
            sp for sp in collector.spans() if sp.name == "migration_attempt"
        ]
        assert attempts, "no migration_attempt span recorded after worker kill"
        stats = front_rt.egress.stats()
        assert any(
            st["consecutive_failures"] >= 1 or st["stalls_total"] >= 1
            for st in stats.values()
        ), f"egress breaker stats show no recorded failure: {stats}"
        print(
            f"chaos-smoke: migration spans={len(attempts)}, "
            f"egress stats={stats}", flush=True,
        )

    frontend.cancel()
    for rt, task in workers:
        task.cancel()
        try:
            await rt.shutdown()
        except (ConnectionError, OSError):
            pass  # the killed worker is already down
    await front_rt.shutdown()
    await store.stop()
    return text


async def run() -> None:
    baseline = await run_cluster(num_workers=1, kill_mid_stream=False)
    chaotic = await run_cluster(num_workers=2, kill_mid_stream=True)
    assert baseline, "baseline deployment streamed nothing"
    assert chaotic == baseline, (
        "stream under worker-kill diverged from the no-fault run:\n"
        f"  fault : {chaotic!r}\n  clean : {baseline!r}"
    )
    print(
        f"chaos-smoke OK: {len(chaotic)} chars bit-identical under "
        "worker-kill mid-stream; migration + breaker metrics populated",
        flush=True,
    )


def main() -> int:
    asyncio.run(run())
    return 0


if __name__ == "__main__":
    sys.exit(main())
