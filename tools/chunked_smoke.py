"""Chunked-scheduler smoke: a mocker-backed frontend with
``--scheduling chunked`` must stream a short request's first token while
a concurrent long prefill is still running.

This is the user-visible contract of the token-budget scheduler (ISSUE 3):
a long prompt streams through chunk-sized steps instead of monopolizing
the engine, so concurrent short requests keep their TTFT. Under the wave
scheduler the short request would queue behind the whole long prefill.

CI usage (`.github/workflows/ci.yml` chunked-smoke step) and local:

    python tools/chunked_smoke.py

Boots a store + chunked mocker + frontend in one process, fires a long
(~8000-token) streaming request and immediately after a short one, and
asserts the short's first streamed token arrives BEFORE the long's
(i.e. before the long prefill completes). Exits non-zero on violation.
"""

from __future__ import annotations

import asyncio
import sys
import time
from pathlib import Path

# Runnable straight from a checkout (CI also pip-installs the package).
sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


async def first_sse_token_time(session, url: str, body: dict) -> float:
    """POST a streaming chat completion; return wall-clock time of the
    first SSE data chunk that carries content."""
    async with session.post(url, json=body) as resp:
        assert resp.status == 200, await resp.text()
        async for raw in resp.content:
            line = raw.decode("utf-8", "replace").strip()
            if line.startswith("data:") and "[DONE]" not in line:
                return time.perf_counter()
    raise AssertionError("stream ended without a data chunk")


async def run() -> None:
    import aiohttp

    from dynamo_tpu.backends.mocker import run_mocker
    from dynamo_tpu.frontend.main import run_frontend
    from dynamo_tpu.llm.mocker import MockEngineArgs
    from dynamo_tpu.runtime import DistributedRuntime
    from dynamo_tpu.runtime.store import StoreServer

    store = StoreServer()
    await store.start()
    worker_rt = await DistributedRuntime.create(store.address)
    served = asyncio.Event()
    worker = asyncio.create_task(
        run_mocker(
            worker_rt,
            model_name="mock",
            engine_args=MockEngineArgs(
                num_kv_blocks=8192,
                block_size=8,
                scheduling="chunked",
                prefill_chunk=128,
                max_num_batched_tokens=1024,
                # Real-time cost model: the ~8000-token prefill takes
                # ~64 chunk-steps (>100 ms); the short request's mixed
                # step beats it by a wide, CI-safe margin.
                speedup_ratio=1.0,
            ),
            served_event=served,
        )
    )
    await asyncio.wait_for(served.wait(), 30)
    front_rt = await DistributedRuntime.create(store.address)
    ready = asyncio.Event()
    services: list = []
    frontend = asyncio.create_task(
        run_frontend(
            front_rt, http_host="127.0.0.1", http_port=0,
            router_mode="kv", ready_event=ready, service_out=services,
        )
    )
    await asyncio.wait_for(ready.wait(), 30)
    base = f"http://127.0.0.1:{services[0].port}"

    async with aiohttp.ClientSession() as s:
        for _ in range(200):
            async with s.get(f"{base}/v1/models") as r:
                if (await r.json())["data"]:
                    break
            await asyncio.sleep(0.05)
        else:
            raise TimeoutError("model never appeared on frontend")

        url = f"{base}/v1/chat/completions"

        def body(content: str) -> dict:
            return {
                "model": "mock",
                "messages": [{"role": "user", "content": content}],
                "max_tokens": 4,
                "stream": True,
            }

        long_task = asyncio.create_task(
            first_sse_token_time(s, url, body("x" * 8000))
        )
        await asyncio.sleep(0.02)  # the long prefill is now in flight
        t_short_start = time.perf_counter()
        t_short_first = await first_sse_token_time(s, url, body("short hello"))
        t_long_first = await long_task

        assert t_short_first < t_long_first, (
            f"short first token ({t_short_first - t_short_start:.3f}s after "
            f"submit) arrived AFTER the long prefill completed — the "
            f"chunked scheduler failed to interleave"
        )
        print(
            "chunked-smoke OK: short first token beat the long prefill by "
            f"{(t_long_first - t_short_first) * 1e3:.1f} ms", flush=True,
        )

    for task in (worker, frontend):
        task.cancel()
    for rt in (worker_rt, front_rt):
        await rt.shutdown()
    await store.stop()


def main() -> int:
    asyncio.run(run())
    return 0


if __name__ == "__main__":
    sys.exit(main())
