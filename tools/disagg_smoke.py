"""Streaming-disaggregation smoke: the real OpenAI frontend over a
1-prefill + 1-decode mocker fleet (ISSUE 17). A long chat prompt routes
through the decode worker, whose `DisaggRouter` ships the prefill to the
prefill pool over the work queue; committed KV chunk windows stream back
over the cursor plane WHILE the prefill is still chunking.

Asserts the user-visible contract:

- the stream is byte-identical to a single aggregated worker serving the
  same request (disagg moves WHERE tokens are computed, never which);
- the handoff actually streamed (``dynamo_disagg_handoffs_streamed_
  total`` on the decode worker's /metrics moved) with zero fallbacks;
- at least one chunk landed BEFORE prefill completion (``dynamo_disagg_
  early_chunks_total`` >= 1) — transfer overlapped compute, which is the
  entire point of the subsystem.

CI usage (`.github/workflows/ci.yml` disagg-smoke step) and local:

    python tools/disagg_smoke.py
"""

from __future__ import annotations

import asyncio
import sys
from pathlib import Path

# Runnable straight from a checkout (CI also pip-installs the package).
sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from tools.megastep_smoke import stream_text  # noqa: E402

# Long enough that the rendered prompt spans many KV blocks and far
# exceeds the disagg router's local-prefill ceiling below.
PROMPT = "streaming disaggregation smoke " * 40
BODY = {
    "model": "mock",
    "messages": [{"role": "user", "content": PROMPT}],
    "max_tokens": 24,
    "temperature": 0,
    "stream": True,
}


def _engine_args():
    from dynamo_tpu.llm.mocker import MockEngineArgs

    # Tight prefill chunks so the remote prefill commits many cursor
    # advances — the decode side must catch at least one mid-prefill.
    return MockEngineArgs(
        num_kv_blocks=4096,
        block_size=8,
        speedup_ratio=20.0,
        scheduling="chunked",
        prefill_chunk=8,
    )


def _disagg_config():
    from dynamo_tpu.llm.disagg import DisaggConfig

    return DisaggConfig(max_local_prefill_length=16)


async def _boot(roles: list[str]):
    """Store + one mocker worker per role (each with a live status
    server) + a frontend; returns (handles, base_url)."""
    from dynamo_tpu.backends.mocker import run_mocker
    from dynamo_tpu.frontend.main import run_frontend
    from dynamo_tpu.runtime import DistributedRuntime
    from dynamo_tpu.runtime.status_server import SystemStatusServer
    from dynamo_tpu.runtime.store import StoreServer

    store = StoreServer()
    await store.start()
    runtimes, tasks, statuses = [], [], []
    for role in roles:
        rt = await DistributedRuntime.create(store.address)
        status = SystemStatusServer(host="127.0.0.1", port=0)
        await status.start()
        rt.status = status
        statuses.append(status)
        served = asyncio.Event()
        component = role if role != "aggregated" else "backend"
        tasks.append(
            asyncio.create_task(
                run_mocker(
                    rt, model_name="mock", component=component,
                    engine_args=_engine_args(), served_event=served,
                    role=role, disagg_config=_disagg_config(),
                )
            )
        )
        await asyncio.wait_for(served.wait(), 30)
        runtimes.append(rt)
    front_rt = await DistributedRuntime.create(store.address)
    runtimes.append(front_rt)
    ready = asyncio.Event()
    services: list = []
    tasks.append(
        asyncio.create_task(
            run_frontend(
                front_rt, http_host="127.0.0.1", http_port=0,
                ready_event=ready, service_out=services,
            )
        )
    )
    await asyncio.wait_for(ready.wait(), 30)
    return (store, runtimes, tasks, statuses), f"http://127.0.0.1:{services[0].port}"


async def _teardown(handles) -> None:
    store, runtimes, tasks, statuses = handles
    for t in tasks:
        t.cancel()
    for rt in runtimes:
        await rt.shutdown()
    for st in statuses:
        await st.stop()
    await store.stop()


async def _wait_model(s, base: str) -> None:
    for _ in range(200):
        async with s.get(f"{base}/v1/models") as r:
            if (await r.json())["data"]:
                return
        await asyncio.sleep(0.05)
    raise TimeoutError("model never appeared on frontend")


def _gauge(metrics: str, name: str) -> float:
    for line in metrics.splitlines():
        if line.startswith(name):
            return float(line.rsplit(None, 1)[-1])
    raise AssertionError(f"gauge {name!r} not on /metrics")


async def run() -> None:
    import aiohttp

    # Reference: one aggregated worker streaming the same request.
    handles, base = await _boot(["aggregated"])
    try:
        async with aiohttp.ClientSession() as s:
            await _wait_model(s, base)
            want = await stream_text(s, f"{base}/v1/chat/completions", dict(BODY))
    finally:
        await _teardown(handles)
    assert want, "aggregated reference streamed nothing"

    # The disagg fleet: 1 prefill + 1 decode worker. Only the decode
    # worker registers with the frontend; the prefill worker serves the
    # namespace work queue and advertises chunk commits on the cursor
    # plane as they land.
    handles, base = await _boot(["prefill", "decode"])
    try:
        decode_status = handles[3][1]
        async with aiohttp.ClientSession() as s:
            await _wait_model(s, base)
            got = await stream_text(s, f"{base}/v1/chat/completions", dict(BODY))
            async with s.get(
                f"http://127.0.0.1:{decode_status.port}/metrics"
            ) as r:
                assert r.status == 200
                metrics = await r.text()
    finally:
        await _teardown(handles)

    assert got == want, (
        f"disagg stream diverged from the aggregated reference:\n"
        f"  want: {want!r}\n  got:  {got!r}"
    )
    streamed = _gauge(metrics, "dynamo_disagg_handoffs_streamed_total")
    early = _gauge(metrics, "dynamo_disagg_early_chunks_total")
    fallbacks = _gauge(metrics, "dynamo_disagg_handoff_fallback_total")
    chunks = _gauge(metrics, "dynamo_disagg_chunks_pulled_total")
    assert streamed >= 1, "the request never took the streaming handoff"
    assert early >= 1, (
        "no chunk was pulled before prefill completion — transfer never "
        "overlapped compute"
    )
    assert fallbacks == 0, f"{fallbacks} handoffs fell back in a healthy fleet"
    print(
        f"disagg-smoke OK: stream byte-identical to the aggregated run; "
        f"{int(streamed)} streaming handoff(s), {int(chunks)} chunk(s) "
        f"pulled ({int(early)} before prefill completion), 0 fallbacks",
        flush=True,
    )


def main() -> int:
    asyncio.run(run())
    return 0


if __name__ == "__main__":
    sys.exit(main())
