"""dynacheck: interprocedural concurrency analysis + exhaustive invariant
checking for the dynamo-tpu engine core.

Two engines, both stdlib-only, both wired into CI as a hard gate ahead of
tier-1 (``python -m tools.dynacheck``):

**Engine A — interprocedural dynalint v2** (``callgraph`` + ``interproc``):
builds a project-wide call graph over ``dynamo_tpu/`` and runs dataflow
rules a single-function AST pass structurally cannot express — transitive
blocking-call reachability into the step-loop hot paths, lock-acquisition-
order extraction with deadlock-cycle detection, holds-lock pragma
verification along call paths, coroutine-leak dataflow, and the
cursor-discipline rule guarding ``num_computed_tokens`` / pinned-hash /
refcount state.

**Engine B — exhaustive interleaving explorer** (``explore`` + ``models``):
small executable models of the three hairiest state machines (the block
allocator, the async-exec + megastep rollback cursor, the egress circuit
breaker) explored exhaustively over all interleavings up to a bounded
depth, with invariant assertions at every reachable state. The allocator
and breaker models drive the REAL production classes (both are pure
Python); the cursor model mirrors the plan/dispatch/commit/rollback
semantics against a synchronous reference trace.

Every rule and every invariant is provably able to fire: the fixture
suite in ``tests/test_dynacheck.py`` seeds each violation and asserts it
is caught. The checked invariants are catalogued in ``ANALYSIS.md``.
"""

from __future__ import annotations
