"""CLI: ``python -m tools.dynacheck`` (the CI gate).

Runs both engines over ``dynamo_tpu/`` by default. Exit 0 when the tree
is clean (zero unpragma'd interprocedural findings AND zero model
invariant violations), 1 on findings/violations, 2 on usage errors.

``--engine a|b`` narrows to one engine; ``--rules`` narrows Engine A to
a comma-separated subset; ``--pragmas`` prints the in-source suppression
inventory (what tests/test_dynacheck.py pins); ``--no-cache`` bypasses
the source-hash keyed Engine A cache.

``--knobs-md`` emits the generated README knob table (the block between
the ``<!-- knobs:begin -->`` / ``<!-- knobs:end -->`` markers);
``--knob-drift`` exits 1 if the README block differs from what
``--knobs-md`` would emit — the CI drift gate.
"""

from __future__ import annotations

import argparse
import sys
import time
from pathlib import Path

from tools.dynacheck import cache as CA
from tools.dynacheck import config as C
from tools.dynacheck.callgraph import build_project, iter_py_files
from tools.dynacheck.explore import explore
from tools.dynacheck.interproc import run_all
from tools.dynacheck.report import Report, stats_for


def run(
    paths: list[Path],
    repo_root: Path,
    engine: str = "all",
    rules: set[str] | None = None,
    use_cache: bool = True,
) -> Report:
    report = Report()
    if engine in ("a", "all"):
        files = iter_py_files(paths, repo_root)
        key = CA.tree_key(files, repo_root) if use_cache else None
        cached = CA.load(repo_root, key) if key else None
        if cached is not None:
            findings, pragmas, functions, edges = cached
        else:
            project = build_project(paths, repo_root)
            findings = run_all(project)
            pragmas = list(project.pragmas)
            functions, edges = stats_for(project)
            if key:
                CA.store(repo_root, key, findings, pragmas, functions, edges)
        if rules is not None:
            findings = [
                f for f in findings
                if f.rule in rules or f.rule == "malformed-pragma"
            ]
        report.findings = findings
        report.pragmas = pragmas
        report.functions = functions
        report.resolved_edges = edges
    if engine in ("b", "all"):
        from tools.dynacheck.models import ALL_MODELS

        for model_cls in ALL_MODELS:
            report.models.append(explore(model_cls()))
    return report


KNOBS_BEGIN = "<!-- knobs:begin -->"
KNOBS_END = "<!-- knobs:end -->"


def knobs_markdown() -> str:
    """The generated knob table, markers included.

    This is the one place the checker imports product code — the table
    documents runtime behavior, so it renders from the live registry
    (stdlib-only module, import is side-effect free). The static
    config-knob rule never does this.
    """
    from dynamo_tpu import knobs

    lines = [
        KNOBS_BEGIN,
        "<!-- generated: python -m tools.dynacheck --knobs-md; "
        "CI fails on drift (--knob-drift) -->",
        "| Knob | Default | Type | What it does |",
        "|---|---|---|---|",
    ]
    section = None
    for k in sorted(knobs.KNOBS.values(), key=lambda k: (k.section, k.name)):
        if k.section != section:
            section = k.section
            lines.append(f"| **{section}** | | | |")
        default = f"`{k.default}`" if k.default != "" else "*(empty)*"
        lines.append(f"| `{k.name}` | {default} | {k.kind} | {k.doc} |")
    lines.append(KNOBS_END)
    return "\n".join(lines) + "\n"


def knob_drift(repo_root: Path) -> int:
    want = knobs_markdown()
    readme = repo_root / "README.md"
    try:
        text = readme.read_text(encoding="utf-8")
    except OSError:
        print("knob-drift: README.md not found", file=sys.stderr)
        return 1
    begin = text.find(KNOBS_BEGIN)
    end = text.find(KNOBS_END)
    if begin < 0 or end < 0:
        print(
            f"knob-drift: README.md lacks the {KNOBS_BEGIN} / {KNOBS_END} "
            "markers — paste the --knobs-md output between them",
            file=sys.stderr,
        )
        return 1
    have = text[begin : end + len(KNOBS_END)] + "\n"
    if have != want:
        print(
            "knob-drift: README.md knob table is stale — regenerate with "
            "`python -m tools.dynacheck --knobs-md` and paste it between "
            "the markers",
            file=sys.stderr,
        )
        return 1
    print("knob-drift: README.md knob table matches the registry")
    return 0


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.dynacheck",
        description="dynamo-tpu interprocedural analysis + invariant models",
    )
    ap.add_argument(
        "paths", nargs="*", default=list(C.DEFAULT_PATHS),
        help="files or directories to analyze (default: dynamo_tpu/)",
    )
    ap.add_argument("--engine", choices=("a", "b", "all"), default="all")
    ap.add_argument(
        "--rules", default=None,
        help=f"comma-separated subset of: {', '.join(C.ALL_RULES)}",
    )
    ap.add_argument(
        "--pragmas", action="store_true",
        help="also list every dynacheck suppression pragma in the tree",
    )
    ap.add_argument("--no-cache", action="store_true")
    ap.add_argument(
        "--knobs-md", action="store_true",
        help="print the generated README knob table and exit",
    )
    ap.add_argument(
        "--knob-drift", action="store_true",
        help="exit 1 if the README knob table differs from --knobs-md",
    )
    args = ap.parse_args(argv)

    if args.knobs_md:
        sys.stdout.write(knobs_markdown())
        return 0
    if args.knob_drift:
        return knob_drift(Path(__file__).resolve().parents[2])

    rules = None
    if args.rules:
        rules = {r.strip() for r in args.rules.split(",") if r.strip()}
        unknown = rules - set(C.ALL_RULES)
        if unknown:
            print(f"unknown rules: {', '.join(sorted(unknown))}", file=sys.stderr)
            return 2

    paths = [Path(p) for p in args.paths]
    for p in paths:
        if not p.exists():
            print(f"no such path: {p}", file=sys.stderr)
            return 2

    repo_root = Path(__file__).resolve().parents[2]
    t0 = time.monotonic()
    report = run(
        paths, repo_root, engine=args.engine, rules=rules,
        use_cache=not args.no_cache,
    )
    sys.stdout.write(report.render(show_pragmas=args.pragmas))
    # Wall-clock to stderr only: the stdout report stays byte-identical.
    print(f"dynacheck ran in {time.monotonic() - t0:.1f}s", file=sys.stderr)
    return 0 if report.ok else 1


if __name__ == "__main__":
    sys.exit(main())
