"""CLI: ``python -m tools.dynacheck`` (the CI gate).

Runs both engines over ``dynamo_tpu/`` by default. Exit 0 when the tree
is clean (zero unpragma'd interprocedural findings AND zero model
invariant violations), 1 on findings/violations, 2 on usage errors.

``--engine a|b`` narrows to one engine; ``--rules`` narrows Engine A to
a comma-separated subset; ``--pragmas`` prints the in-source suppression
inventory (what tests/test_dynacheck.py pins); ``--no-cache`` bypasses
the source-hash keyed Engine A cache.
"""

from __future__ import annotations

import argparse
import sys
import time
from pathlib import Path

from tools.dynacheck import cache as CA
from tools.dynacheck import config as C
from tools.dynacheck.callgraph import build_project, iter_py_files
from tools.dynacheck.explore import explore
from tools.dynacheck.interproc import run_all
from tools.dynacheck.report import Report, stats_for


def run(
    paths: list[Path],
    repo_root: Path,
    engine: str = "all",
    rules: set[str] | None = None,
    use_cache: bool = True,
) -> Report:
    report = Report()
    if engine in ("a", "all"):
        files = iter_py_files(paths, repo_root)
        key = CA.tree_key(files, repo_root) if use_cache else None
        cached = CA.load(repo_root, key) if key else None
        if cached is not None:
            findings, pragmas, functions, edges = cached
        else:
            project = build_project(paths, repo_root)
            findings = run_all(project)
            pragmas = list(project.pragmas)
            functions, edges = stats_for(project)
            if key:
                CA.store(repo_root, key, findings, pragmas, functions, edges)
        if rules is not None:
            findings = [
                f for f in findings
                if f.rule in rules or f.rule == "malformed-pragma"
            ]
        report.findings = findings
        report.pragmas = pragmas
        report.functions = functions
        report.resolved_edges = edges
    if engine in ("b", "all"):
        from tools.dynacheck.models import ALL_MODELS

        for model_cls in ALL_MODELS:
            report.models.append(explore(model_cls()))
    return report


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.dynacheck",
        description="dynamo-tpu interprocedural analysis + invariant models",
    )
    ap.add_argument(
        "paths", nargs="*", default=list(C.DEFAULT_PATHS),
        help="files or directories to analyze (default: dynamo_tpu/)",
    )
    ap.add_argument("--engine", choices=("a", "b", "all"), default="all")
    ap.add_argument(
        "--rules", default=None,
        help=f"comma-separated subset of: {', '.join(C.ALL_RULES)}",
    )
    ap.add_argument(
        "--pragmas", action="store_true",
        help="also list every dynacheck suppression pragma in the tree",
    )
    ap.add_argument("--no-cache", action="store_true")
    args = ap.parse_args(argv)

    rules = None
    if args.rules:
        rules = {r.strip() for r in args.rules.split(",") if r.strip()}
        unknown = rules - set(C.ALL_RULES)
        if unknown:
            print(f"unknown rules: {', '.join(sorted(unknown))}", file=sys.stderr)
            return 2

    paths = [Path(p) for p in args.paths]
    for p in paths:
        if not p.exists():
            print(f"no such path: {p}", file=sys.stderr)
            return 2

    repo_root = Path(__file__).resolve().parents[2]
    t0 = time.monotonic()
    report = run(
        paths, repo_root, engine=args.engine, rules=rules,
        use_cache=not args.no_cache,
    )
    sys.stdout.write(report.render(show_pragmas=args.pragmas))
    # Wall-clock to stderr only: the stdout report stays byte-identical.
    print(f"dynacheck ran in {time.monotonic() - t0:.1f}s", file=sys.stderr)
    return 0 if report.ok else 1


if __name__ == "__main__":
    sys.exit(main())
