"""Source-hash keyed cache for the Engine A analysis (the call-graph
build + interprocedural rules — the expensive half of a dynacheck run).

The key is a sha256 over every scanned file's (path, bytes) in sorted
order PLUS the analyzer's own sources (tools/dynacheck + tools/dynalint,
whose config feeds the rule tables) — any edit to either misses. The
cached artifact is the Engine A result (findings + pragma inventory +
graph stats) as JSON; Engine B always executes (the models ARE the
check, and they run in seconds).

Layout: ``.dynacheck_cache/<key>.json`` under the repo root; the CI job
caches this directory keyed on the same file set. ``--no-cache``
bypasses both read and write.
"""

from __future__ import annotations

import hashlib
import json
from pathlib import Path

from tools.dynacheck.callgraph import Pragma
from tools.dynacheck.interproc import Finding

CACHE_DIR = ".dynacheck_cache"
_VERSION = 2


def tree_key(files: list[Path], repo_root: Path) -> str:
    h = hashlib.sha256(b"dynacheck-v%d" % _VERSION)
    tool_dir = Path(__file__).resolve().parent
    tool_files = sorted(tool_dir.rglob("*.py"))
    tool_files += sorted((tool_dir.parent / "dynalint").rglob("*.py"))
    # The config-knob rule reads the README (doc-coverage check), so a
    # doc edit must miss the cache too.
    readme = repo_root / "README.md"
    if readme.is_file():
        tool_files.append(readme)
    for f in tool_files + sorted(files):
        try:
            rel = f.resolve().relative_to(repo_root.resolve()).as_posix()
        except ValueError:
            rel = f.as_posix()
        h.update(rel.encode())
        h.update(b"\0")
        h.update(f.read_bytes())
        h.update(b"\0")
    return h.hexdigest()


def load(repo_root: Path, key: str):
    """Returns (findings, pragmas, functions, edges) or None on miss."""
    p = repo_root / CACHE_DIR / f"{key}.json"
    try:
        data = json.loads(p.read_text(encoding="utf-8"))
    except (OSError, ValueError):
        return None
    try:
        findings = [Finding(**f) for f in data["findings"]]
        pragmas = [Pragma(**p) for p in data["pragmas"]]
        return findings, pragmas, data["functions"], data["edges"]
    except (KeyError, TypeError):
        return None


def store(
    repo_root: Path, key: str,
    findings: list[Finding], pragmas: list[Pragma],
    functions: int, edges: int,
) -> None:
    d = repo_root / CACHE_DIR
    payload = {
        "findings": [vars(f) for f in findings],
        "pragmas": [vars(p) for p in pragmas],
        "functions": functions,
        "edges": edges,
    }
    try:
        d.mkdir(exist_ok=True)
        tmp = d / f".{key}.tmp"
        tmp.write_text(json.dumps(payload, sort_keys=True), encoding="utf-8")
        tmp.replace(d / f"{key}.json")
    except OSError:
        pass  # cache is best-effort; the analysis can always re-run
