"""Project-wide call graph + await graph construction (Engine A's base).

Stdlib ``ast`` only. One parse per file produces, for every function
(including nested defs and methods, dotted qualnames like
``EngineCore._plan_megastep.commit``):

- resolved call sites (callee -> project function), with the set of lock
  identities lexically held at each call,
- lock acquisitions (``with``/``async with`` over known locks), with the
  locks already held when each is taken,
- attribute writes (assign / augassign / del / mutator-method calls),
- per-call usage context for coroutine-leak dataflow (awaited, spawned,
  returned, bound-and-reused, dropped).

Call resolution is deliberately project-native and heuristic — this is a
lint layer, not a type checker. A call resolves when the callee is:
``self.m`` -> method ``m`` of the enclosing class; a typed attribute
(``self.x = ClassName(...)`` in ``__init__`` or an annotated ctor param)
-> that class's method; a local or imported module function; or a method
name defined exactly ONCE across the project (unique-name fallback).
Ambiguous calls stay unresolved and no rule fires through them: the tool
under-approximates rather than spamming.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from pathlib import Path

from tools.dynacheck import config as C

# Lock identity: (scope, attr) — scope is the owning class name, or the
# repo-relative module path for module-level locks.
LockId = tuple[str, str]


@dataclass(frozen=True)
class LockAcquire:
    lock: LockId
    line: int
    held_before: tuple[LockId, ...]


@dataclass(frozen=True)
class AttrWrite:
    attr: str
    line: int
    col: int
    kind: str  # "assign" | "augassign" | "del" | "mutate:<method>"
    # Dotted receiver text ("seq", "self", "blk", ...); "<local>" /
    # "<global>" for bare-name stores (registry-drift needs module
    # globals), "self(alias)" for writes through a `st = self.X` alias.
    receiver: str
    held: tuple[LockId, ...] = ()  # locks lexically held at the write


@dataclass
class CallSite:
    line: int
    col: int
    raw: str                     # callee as written ("self.allocator.commit")
    targets: list[str] = field(default_factory=list)  # resolved func keys
    awaited: bool = False
    usage: str = "other"         # await|sink|return|yield|bound:<n>|dropped|other
    held_locks: tuple[LockId, ...] = ()


def _is_generator(node) -> bool:
    stack = list(node.body)
    while stack:
        n = stack.pop()
        if isinstance(n, (ast.Yield, ast.YieldFrom)):
            return True
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(n))
    return False


@dataclass
class FuncInfo:
    path: str                    # repo-relative posix path
    qualname: str                # dotted nesting: Class.method.nested
    lineno: int
    is_async: bool = False
    is_generator: bool = False
    holds_pragmas: frozenset[str] = frozenset()
    calls: list[CallSite] = field(default_factory=list)
    # Callables handed to thread contexts (to_thread / run_in_executor /
    # submit / Thread(target=...)): resolved like calls; loop-affinity
    # BFS roots.
    spawn_sites: list[CallSite] = field(default_factory=list)
    lock_acquires: list[LockAcquire] = field(default_factory=list)
    writes: list[AttrWrite] = field(default_factory=list)
    # Direct blocking sites inside THIS function's own body (line, what).
    sync_sites: list[tuple[int, str]] = field(default_factory=list)
    # AST def node (coroutine-leak's bound-name reuse scan needs the body).
    node: object = field(default=None, repr=False, compare=False)

    @property
    def key(self) -> str:
        return f"{self.path}::{self.qualname}"

    @property
    def name(self) -> str:
        return self.qualname.rsplit(".", 1)[-1]


@dataclass
class Project:
    root: Path
    functions: dict[str, FuncInfo] = field(default_factory=dict)
    # class name -> {path of files defining it}
    classes: dict[str, set[str]] = field(default_factory=dict)
    # known locks: (scope, attr) -> defining (path, line)
    locks: dict[LockId, tuple[str, int]] = field(default_factory=dict)
    # callers index (filled by resolve): func key -> [(caller key, CallSite)]
    callers: dict[str, list[tuple[str, CallSite]]] = field(default_factory=dict)
    # parsed module per file (wire/knob rules re-walk these; NOT cached
    # — the cache stores findings only)
    trees: dict[str, ast.Module] = field(default_factory=dict)
    # per-file import map: local name -> dotted target (module or obj)
    imports_by_file: dict[str, dict[str, str]] = field(default_factory=dict)
    # pragma inventory: (path, rule) -> [(line, reason)]
    pragmas: list = field(default_factory=list)
    # pragma errors (malformed) as (path, line, message)
    pragma_errors: list = field(default_factory=list)
    # suppressed (path, statement-span) per rule, for finding filtering:
    # rule -> set of (path, line) covering every line of pragma'd statements
    allow_lines: dict[str, set[tuple[str, int]]] = field(default_factory=dict)
    # dynalint sync-ok pragma lines (path, line): a transitive finding whose
    # blocking site is an intentional, already-reviewed sync is not news.
    sync_ok_lines: set[tuple[str, int]] = field(default_factory=set)

    def suppressed(self, rule: str, path: str, line: int) -> bool:
        return (path, line) in self.allow_lines.get(rule, ())


# ---------------------------------------------------------------------------
# Helpers (shared shapes with dynalint, kept dependency-free of its linter)
# ---------------------------------------------------------------------------


def dotted_name(node: ast.AST) -> str | None:
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _is_sync_site(node: ast.Call) -> str | None:
    """dynalint rule-7 vocabulary: device->host sync calls."""
    func = node.func
    if isinstance(func, ast.Attribute):
        if func.attr in C.HOST_SYNC_METHODS:
            return f".{func.attr}()"
        if func.attr == "asarray" and dotted_name(func.value) in C.HOST_SYNC_ASARRAY_ROOTS:
            return "np.asarray()"
        if func.attr in C.HOST_SYNC_FNS:
            return f"{func.attr}()"
    elif isinstance(func, ast.Name) and func.id in C.HOST_SYNC_FNS:
        return f"{func.id}()"
    d = dotted_name(func)
    if d in C.BLOCKING_CALLS:
        return f"{d}()"
    if d and d.split(".")[0] in C.BLOCKING_ROOTS:
        return f"{d}()"
    return None


_MUTATOR_METHODS = {
    "append", "extend", "insert", "add", "update", "setdefault",
    "pop", "popleft", "popitem", "remove", "discard", "clear",
    "appendleft", "rotate", "sort", "reverse",
}

# Parent nodes "transparent" for coroutine usage classification: a call
# inside one of these is classified by the node above it (e.g. the list
# handed to gather(*coros)).
_TRANSPARENT = (ast.List, ast.Tuple, ast.Set, ast.Starred, ast.IfExp, ast.NamedExpr)


class _FileScanner(ast.NodeVisitor):
    """One pass over a module: collects FuncInfos, lock defs, class defs."""

    def __init__(self, path: str, tree: ast.Module, project: Project):
        self.path = path
        self.tree = tree
        self.project = project
        self.module_func = FuncInfo(path=path, qualname="<module>", lineno=0)
        self._class_stack: list[str] = []
        self._func_stack: list[FuncInfo] = []
        self._held: list[LockId] = []
        # Local lock aliases within the current function: name -> LockId.
        self._lock_aliases: list[dict[str, LockId]] = []
        # Local attribute aliases (`st = self.transfer_stats`): name -> attr.
        self._attr_aliases: list[dict[str, str]] = []
        # Per-function `global` declarations.
        self._globals: list[set[str]] = []
        # self.<attr> -> class-name type hints, per enclosing class.
        self.attr_types: dict[tuple[str, str], str] = {}
        # parameter annotations: (qualname, param) -> class name
        self.param_types: dict[tuple[str, str], str] = {}
        # Imports: local name -> dotted target module/obj.
        self.imports: dict[str, str] = {}
        self._parents: dict[ast.AST, ast.AST] = {}
        for node in ast.walk(tree):
            for child in ast.iter_child_nodes(node):
                self._parents[child] = node

    # -- scope bookkeeping -------------------------------------------------

    def _cur(self) -> FuncInfo:
        return self._func_stack[-1] if self._func_stack else self.module_func

    def _qual(self, name: str) -> str:
        if self._func_stack:
            return f"{self._func_stack[-1].qualname}.{name}"
        if self._class_stack:
            return f"{'.'.join(self._class_stack)}.{name}"
        return name

    def visit_Import(self, node: ast.Import) -> None:
        for a in node.names:
            self.imports[a.asname or a.name.split(".")[0]] = a.name
        self.generic_visit(node)

    def visit_ImportFrom(self, node: ast.ImportFrom) -> None:
        if node.module:
            for a in node.names:
                self.imports[a.asname or a.name] = f"{node.module}.{a.name}"
        self.generic_visit(node)

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        self._class_stack.append(node.name)
        self.project.classes.setdefault(node.name, set()).add(self.path)
        self.generic_visit(node)
        self._class_stack.pop()

    def _enter_func(self, node, is_async: bool) -> None:
        qual = self._qual(node.name)
        info = FuncInfo(
            path=self.path, qualname=qual, lineno=node.lineno, is_async=is_async,
            is_generator=_is_generator(node), node=node,
        )
        self.project.functions[info.key] = info
        self._func_stack.append(info)
        self._lock_aliases.append({})
        self._attr_aliases.append({})
        globals_declared: set[str] = set()
        stack = list(node.body)
        while stack:
            sub = stack.pop()
            if isinstance(sub, ast.Global):
                globals_declared.update(sub.names)
            elif not isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
                stack.extend(ast.iter_child_nodes(sub))
        self._globals.append(globals_declared)
        # Annotated params as type hints (def f(self, core: EngineCore)).
        for arg in node.args.posonlyargs + node.args.args + node.args.kwonlyargs:
            ann = arg.annotation
            if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
                self.param_types[(qual, arg.arg)] = ann.value.strip("\"'")
            else:
                d = dotted_name(ann) if ann is not None else None
                if d:
                    self.param_types[(qual, arg.arg)] = d.rsplit(".", 1)[-1]

    def _exit_func(self) -> None:
        self._func_stack.pop()
        self._lock_aliases.pop()
        self._attr_aliases.pop()
        self._globals.pop()

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self._enter_func(node, is_async=False)
        self.generic_visit(node)
        self._exit_func()

    def visit_AsyncFunctionDef(self, node: ast.AsyncFunctionDef) -> None:
        self._enter_func(node, is_async=True)
        self.generic_visit(node)
        self._exit_func()

    def visit_Lambda(self, node: ast.Lambda) -> None:
        # Lambdas stay attributed to the enclosing function.
        self.generic_visit(node)

    # -- lock tracking -----------------------------------------------------

    def _lock_id_for(self, expr: ast.expr) -> LockId | None:
        """Resolve a with-item context expression to a lock identity."""
        if isinstance(expr, ast.Name) and self._lock_aliases:
            alias = self._lock_aliases[-1].get(expr.id)
            if alias is not None:
                return alias
        # Subscripted lock maps: self._locks[address] -> (Class, _locks[]).
        if isinstance(expr, ast.Subscript):
            base = self._lock_id_for(expr.value)
            if base is not None:
                return (base[0], base[1] + "[]")
            d = dotted_name(expr.value)
            if d and d.rsplit(".", 1)[-1].lower().endswith("locks"):
                return self._attr_lock(d.rsplit(".", 1)[-1] + "[]", expr)
            return None
        d = dotted_name(expr)
        if d is None:
            return None
        last = d.rsplit(".", 1)[-1]
        lock_like = last.lower().endswith(C.LOCK_NAME_SUFFIXES)
        if d.startswith("self."):
            parts = d.split(".")
            if len(parts) == 2:
                if self._class_stack:
                    lid = (self._class_stack[-1], parts[1])
                    if lid in self.project.locks or lock_like:
                        return lid
                return None
            # self.a.b (a lock reached through an attribute): identify by
            # the attr name against the registered-lock index below.
            if lock_like:
                return self._attr_lock(last, expr)
            return None
        if "." not in d:
            # Module-level lock (bare name): registered or lock-like.
            lid = (self.path, d)
            if lid in self.project.locks or (
                lock_like and not self._is_local(d)
            ):
                return lid
            return None
        # Foreign receiver (`first._step_lock`): identify by unique attr
        # name across registered locks, so two instances of one class map
        # to ONE identity — exactly what lock-order needs.
        if lock_like:
            return self._attr_lock(last, expr)
        return None

    def _attr_lock(self, attr: str, expr: ast.expr) -> LockId | None:
        owners = [lid for lid in self.project.locks if lid[1] == attr]
        if len({o[0] for o in owners}) == 1:
            return owners[0]
        # Unregistered / ambiguous: scope to this file.
        return (self.path, attr)

    def _is_local(self, name: str) -> bool:
        return bool(self._func_stack)  # conservative: bare names in funcs are locals

    def _visit_with(self, node) -> None:
        added: list[LockId] = []
        for item in node.items:
            lid = self._lock_id_for(item.context_expr)
            if lid is not None:
                self._cur().lock_acquires.append(
                    LockAcquire(lid, item.context_expr.lineno, tuple(self._held))
                )
                self._held.append(lid)
                added.append(lid)
        self.generic_visit(node)
        for _ in added:
            self._held.pop()

    def visit_With(self, node: ast.With) -> None:
        self._visit_with(node)

    def visit_AsyncWith(self, node: ast.AsyncWith) -> None:
        self._visit_with(node)

    # -- assignments: lock defs, aliases, attr types, writes ---------------

    def visit_Assign(self, node: ast.Assign) -> None:
        value = node.value
        vd = dotted_name(value.func) if isinstance(value, ast.Call) else None
        for target in node.targets:
            td = dotted_name(target)
            # Lock constructor assignment -> register a lock identity.
            if vd in C.LOCK_CONSTRUCTORS and td is not None:
                if td.startswith("self.") and self._class_stack:
                    lid = (self._class_stack[-1], td.split(".", 1)[1])
                elif "." not in td and not self._func_stack:
                    lid = (self.path, td)
                else:
                    lid = None
                if lid is not None:
                    self.project.locks[lid] = (self.path, node.lineno)
            # Typed attribute: self.x = ClassName(...) in any method.
            if (
                vd is not None and td is not None and td.startswith("self.")
                and "." not in td[5:] and self._class_stack
                and vd.rsplit(".", 1)[-1] in self.project.classes
            ):
                self.attr_types[(self._class_stack[-1], td[5:])] = vd.rsplit(".", 1)[-1]
            # self.x = param  where param is annotated -> propagate type.
            if (
                isinstance(value, ast.Name) and td is not None
                and td.startswith("self.") and "." not in td[5:]
                and self._class_stack and self._func_stack
            ):
                t = self.param_types.get((self._cur().qualname, value.id))
                if t and t in self.project.classes:
                    self.attr_types[(self._class_stack[-1], td[5:])] = t
            # Local lock alias: lock = self._locks.setdefault(...), etc.
            if isinstance(target, ast.Name) and self._lock_aliases:
                lid = self._alias_lock_rhs(value)
                if lid is not None:
                    self._lock_aliases[-1][target.id] = lid
                # Attribute alias: `st = self.transfer_stats` — writes
                # through `st` are writes to the attribute.
                vdot = dotted_name(value)
                if vdot and vdot.startswith("self.") and "." not in vdot[5:]:
                    self._attr_aliases[-1][target.id] = vdot[5:]
            self._record_write(target, node, "assign")
        self.generic_visit(node)

    def _alias_lock_rhs(self, value: ast.expr) -> LockId | None:
        """`lock = <expr reaching a lock map or lock attr>` alias."""
        if isinstance(value, ast.Call):
            d = dotted_name(value.func)
            if d and d.rsplit(".", 2)[-1] == "setdefault" and ".locks" in f".{d.lower()}":
                recv = d.rsplit(".", 1)[0]
                last = recv.rsplit(".", 1)[-1]
                if recv.startswith("self.") and self._class_stack:
                    return (self._class_stack[-1], last + "[]")
                return (self.path, last + "[]")
            if d in C.LOCK_CONSTRUCTORS:
                return None  # fresh local lock: no shared identity
        if isinstance(value, (ast.Attribute, ast.Subscript)):
            return self._lock_id_for(value)
        return None

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        self._record_write(node.target, node, "augassign")
        self.generic_visit(node)

    def visit_Delete(self, node: ast.Delete) -> None:
        for target in node.targets:
            self._record_write(target, node, "del")
        self.generic_visit(node)

    def _record_write(self, target: ast.expr, site: ast.AST, kind: str) -> None:
        subscripted = False
        while isinstance(target, (ast.Subscript, ast.Starred)):
            subscripted = subscripted or isinstance(target, ast.Subscript)
            target = target.value
        if isinstance(target, ast.Tuple):
            for el in target.elts:
                self._record_write(el, site, kind)
            return
        line = site.lineno
        col = getattr(site, "col_offset", 0)
        held = tuple(self._held)
        if isinstance(target, ast.Attribute):
            recv = dotted_name(target.value) or "<expr>"
            self._cur().writes.append(
                AttrWrite(target.attr, line, col, kind, recv, held)
            )
            return
        if isinstance(target, ast.Name):
            alias = self._attr_aliases[-1].get(target.id) if self._attr_aliases else None
            if alias is not None and (subscripted or kind.startswith("mutate")):
                self._cur().writes.append(
                    AttrWrite(alias, line, col, kind, "self(alias)", held)
                )
                return
            if not self._func_stack or (
                self._globals and target.id in self._globals[-1]
            ):
                recv = "<global>"
            else:
                # A plain local rebinding is not interesting — but a
                # SUBSCRIPT store through a local can alias shared state;
                # registry-drift treats "<local>" writes as weak evidence.
                recv = "<local>"
                if not subscripted and not kind.startswith("mutate"):
                    return
            self._cur().writes.append(
                AttrWrite(target.id, line, col, kind, recv, held)
            )

    # -- calls -------------------------------------------------------------

    def visit_Call(self, node: ast.Call) -> None:
        raw = dotted_name(node.func) or (
            f"<expr>.{node.func.attr}" if isinstance(node.func, ast.Attribute) else "<expr>"
        )
        cs = CallSite(
            line=node.lineno, col=node.col_offset, raw=raw,
            held_locks=tuple(self._held),
        )
        cs.usage = self._usage_of(node)
        cs.awaited = cs.usage == "await"
        cur = self._cur()
        cur.calls.append(cs)
        sync = _is_sync_site(node)
        if sync is not None:
            cur.sync_sites.append((node.lineno, sync))
        self._record_spawn(node, cur)
        # Mutator-method writes (x.attr.append(...) mutates x.attr).
        if isinstance(node.func, ast.Attribute) and node.func.attr in _MUTATOR_METHODS:
            base = node.func.value
            while isinstance(base, (ast.Subscript, ast.Starred)):
                base = base.value
            held = tuple(self._held)
            if isinstance(base, ast.Attribute):
                recv = dotted_name(base.value) or "<expr>"
                cur.writes.append(
                    AttrWrite(base.attr, node.lineno, node.col_offset,
                              f"mutate:{node.func.attr}", recv, held)
                )
            elif isinstance(base, ast.Name):
                alias = self._attr_aliases[-1].get(base.id) if self._attr_aliases else None
                if alias is not None:
                    cur.writes.append(
                        AttrWrite(alias, node.lineno, node.col_offset,
                                  f"mutate:{node.func.attr}", "self(alias)", held)
                    )
                elif not self._func_stack or (
                    self._globals and base.id in self._globals[-1]
                ):
                    cur.writes.append(
                        AttrWrite(base.id, node.lineno, node.col_offset,
                                  f"mutate:{node.func.attr}", "<global>", held)
                    )
        self.generic_visit(node)

    def _record_spawn(self, node: ast.Call, cur: FuncInfo) -> None:
        """Callable handed to a thread context becomes a spawn site."""
        name = (
            node.func.attr if isinstance(node.func, ast.Attribute)
            else node.func.id if isinstance(node.func, ast.Name) else None
        )
        if name not in C.THREAD_SPAWNERS:
            return
        target: ast.expr | None = None
        if name == "to_thread" and node.args:
            target = node.args[0]
        elif name == "run_in_executor" and len(node.args) >= 2:
            target = node.args[1]
        elif name == "submit" and node.args:
            target = node.args[0]
        elif name == "Thread":
            for kw in node.keywords:
                if kw.arg == "target":
                    target = kw.value
        if target is None:
            return
        raw = dotted_name(target)
        if raw is None and isinstance(target, ast.Attribute):
            raw = f"<expr>.{target.attr}"
        if raw is None:
            return  # lambda / partial: unresolvable, under-approximate
        cur.spawn_sites.append(CallSite(
            line=node.lineno, col=node.col_offset, raw=raw,
        ))

    def _usage_of(self, node: ast.Call) -> str:
        parent = self._parents.get(node)
        while isinstance(parent, _TRANSPARENT):
            parent = self._parents.get(parent)
        if isinstance(parent, ast.Await):
            return "await"
        if isinstance(parent, ast.Call) and parent is not node:
            d = dotted_name(parent.func)
            last = d.rsplit(".", 1)[-1] if d else (
                parent.func.attr if isinstance(parent.func, ast.Attribute) else None
            )
            if last in C.CORO_SINKS:
                return "sink"
            return "other"  # handed to some call: assume ownership moves
        if isinstance(parent, ast.Return):
            return "return"
        if isinstance(parent, (ast.Yield, ast.YieldFrom)):
            return "yield"
        if isinstance(parent, ast.Assign):
            targets = parent.targets
            if len(targets) == 1 and isinstance(targets[0], ast.Name):
                return f"bound:{targets[0].id}"
            return "other"
        if isinstance(parent, ast.Expr):
            return "dropped"
        return "other"


# ---------------------------------------------------------------------------
# Resolution
# ---------------------------------------------------------------------------


def _build_indexes(scanners: list[_FileScanner], project: Project):
    # (class, method) -> key ; module path -> {func name -> key}
    method_index: dict[tuple[str, str], str] = {}
    methods_by_name: dict[str, list[str]] = {}
    module_funcs: dict[tuple[str, str], str] = {}
    funcs_by_name: dict[str, list[str]] = {}
    for info in project.functions.values():
        parts = info.qualname.split(".")
        if len(parts) == 1:
            module_funcs[(info.path, parts[0])] = info.key
            funcs_by_name.setdefault(parts[0], []).append(info.key)
        elif len(parts) == 2 and parts[0] in project.classes:
            method_index[(parts[0], parts[1])] = info.key
            methods_by_name.setdefault(parts[1], []).append(info.key)
    return method_index, methods_by_name, module_funcs, funcs_by_name


def _module_path(dotted: str, root: Path) -> str | None:
    """dynamo_tpu.engine.core -> dynamo_tpu/engine/core.py if it exists."""
    rel = Path(dotted.replace(".", "/") + ".py")
    if (root / rel).is_file():
        return rel.as_posix()
    rel = Path(dotted.replace(".", "/")) / "__init__.py"
    if (root / rel).is_file():
        return rel.as_posix()
    return None


def resolve_calls(scanners: list[_FileScanner], project: Project) -> None:
    method_index, methods_by_name, module_funcs, funcs_by_name = _build_indexes(
        scanners, project
    )
    attr_types: dict[tuple[str, str], str] = {}
    for sc in scanners:
        attr_types.update(sc.attr_types)

    for sc in scanners:
        for info in [
            f for f in project.functions.values() if f.path == sc.path
        ] + [sc.module_func]:
            enclosing_class = (
                info.qualname.split(".")[0]
                if "." in info.qualname and info.qualname.split(".")[0] in project.classes
                else None
            )
            for cs in info.calls:
                cs.targets = _resolve_one(
                    cs.raw, sc, info, enclosing_class, project, attr_types,
                    method_index, methods_by_name, module_funcs, funcs_by_name,
                )
                for t in cs.targets:
                    project.callers.setdefault(t, []).append((info.key, cs))
            for cs in info.spawn_sites:
                cs.targets = _resolve_one(
                    cs.raw, sc, info, enclosing_class, project, attr_types,
                    method_index, methods_by_name, module_funcs, funcs_by_name,
                )


def _resolve_one(
    raw: str, sc: _FileScanner, info: FuncInfo, enclosing_class: str | None,
    project: Project, attr_types: dict[tuple[str, str], str],
    method_index, methods_by_name, module_funcs, funcs_by_name,
) -> list[str]:
    if raw.startswith("<expr>"):
        last = raw.rsplit(".", 1)[-1]
        return _unique(methods_by_name.get(last, []))
    parts = raw.split(".")
    last = parts[-1]
    # self.m() / self.attr.m() with a typed attr.
    if parts[0] == "self" and enclosing_class is not None:
        if len(parts) == 2:
            key = method_index.get((enclosing_class, last))
            if key:
                return [key]
            return _unique(methods_by_name.get(last, []))
        if len(parts) == 3:
            t = attr_types.get((enclosing_class, parts[1]))
            if t is not None:
                key = method_index.get((t, last))
                if key:
                    return [key]
            return _unique(methods_by_name.get(last, []))
        return []
    # Bare name: local module function, else import, else unique global.
    if len(parts) == 1:
        key = module_funcs.get((sc.path, last))
        if key:
            return [key]
        imp = sc.imports.get(last)
        if imp and "." in imp:
            mod, fname = imp.rsplit(".", 1)
            mpath = _module_path(mod, project.root)
            if mpath:
                key = module_funcs.get((mpath, fname))
                if key:
                    return [key]
        return _unique(funcs_by_name.get(last, []))
    # mod.f() via import alias.
    head = parts[0]
    imp = sc.imports.get(head)
    if imp is not None:
        dotted = imp + "." + ".".join(parts[1:-1]) if len(parts) > 2 else imp
        mpath = _module_path(dotted, project.root)
        if mpath:
            key = module_funcs.get((mpath, last))
            if key:
                return [key]
        # imported class: ClassName.method
        cls = imp.rsplit(".", 1)[-1]
        if cls in project.classes and len(parts) == 2:
            key = method_index.get((cls, last))
            if key:
                return [key]
        return []
    # ClassName.method / param.method via annotation.
    if head in project.classes and len(parts) == 2:
        key = method_index.get((head, last))
        if key:
            return [key]
    t = sc.param_types.get((info.qualname, head))
    if t is not None and len(parts) == 2:
        key = method_index.get((t, last))
        if key:
            return [key]
    # obj.m(): unique method name fallback.
    return _unique(methods_by_name.get(last, []))


def _unique(keys: list[str]) -> list[str]:
    return list(keys) if len(set(keys)) == 1 else []


# ---------------------------------------------------------------------------
# Pragmas (`# dynacheck: allow-<rule>(<reason>)`), anchored to the full
# line span of the enclosing statement — the lesson of the dynalint
# multi-line pragma bug, applied from day one here.
# ---------------------------------------------------------------------------

import re

_ALLOW_RE = re.compile(r"dynacheck:\s*allow-([a-z][a-z0-9-]*)\s*\(\s*([^)]*?)\s*\)")
_KNOB_DYNAMIC_RE = re.compile(r"dynacheck:\s*knob-dynamic\s*\(\s*([^)]*?)\s*\)")
_ANY_PRAGMA_RE = re.compile(r"^#+\s*dynacheck:")
_DYNALINT_HOLDS_RE = re.compile(r"dynalint:\s*holds-lock\s*\(\s*([A-Za-z_][A-Za-z0-9_]*)\s*\)")
_DYNALINT_SYNC_OK_RE = re.compile(r"dynalint:\s*sync-ok\b")


@dataclass(frozen=True)
class Pragma:
    path: str
    line: int
    rule: str
    reason: str


def extract_pragmas(path: str, source: str, tree: ast.Module, project: Project) -> None:
    # Span anchoring and comment classification are SHARED with dynalint:
    # the two tiers must never disagree about which lines a pragma covers.
    from tools.dynalint.linter import comment_tokens, covered_lines, statement_spans

    spans = statement_spans(tree)
    holds_lines: list[tuple[int, str]] = []
    for line, text, standalone in comment_tokens(source):
        covered = covered_lines(spans, line, standalone)
        for m in _DYNALINT_HOLDS_RE.finditer(text):
            holds_lines.append((line, m.group(1)))
        if _DYNALINT_SYNC_OK_RE.search(text):
            project.sync_ok_lines.update((path, ln) for ln in covered)
        if not _ANY_PRAGMA_RE.search(text):
            continue
        matched = False
        for m in _KNOB_DYNAMIC_RE.finditer(text):
            # A declared dynamic env-name escape: suppresses config-knob
            # on the statement, recorded in the pragma inventory under
            # its own rule name.
            reason = m.group(1).strip()
            matched = True
            if not reason:
                project.pragma_errors.append((
                    path, line, "knob-dynamic pragma requires a non-empty reason",
                ))
                continue
            project.pragmas.append(Pragma(path, line, "knob-dynamic", reason))
            bucket = project.allow_lines.setdefault(C.RULE_CONFIG_KNOB, set())
            bucket.update((path, ln) for ln in covered)
        for m in _ALLOW_RE.finditer(text):
            rule, reason = m.group(1), m.group(2).strip()
            matched = True
            if rule not in C.ALL_RULES:
                project.pragma_errors.append((
                    path, line,
                    f"allow pragma names unknown rule {rule!r} "
                    f"(known: {', '.join(C.ALL_RULES)})",
                ))
                continue
            if not reason:
                project.pragma_errors.append((
                    path, line, f"allow-{rule} pragma requires a non-empty reason",
                ))
                continue
            project.pragmas.append(Pragma(path, line, rule, reason))
            # Anchored to the enclosing statement's FULL span (plus the
            # statement below, for a standalone pragma-above comment).
            bucket = project.allow_lines.setdefault(rule, set())
            bucket.update((path, ln) for ln in covered)
        if not matched:
            project.pragma_errors.append((
                path, line,
                "unparseable dynacheck pragma; expected "
                "`dynacheck: allow-<rule>(<reason>)`",
            ))
    # Attach dynalint holds-lock pragmas to defs (Engine A rule 3 input).
    if holds_lines:
        for info in [f for f in project.functions.values() if f.path == path]:
            probes = {info.lineno, info.lineno - 1}
            got = {arg for line, arg in holds_lines if line in probes}
            if got:
                info.holds_pragmas = info.holds_pragmas | got


# ---------------------------------------------------------------------------
# Driver
# ---------------------------------------------------------------------------


def _excluded(rel: str) -> bool:
    return any(part in rel for part in C.EXCLUDE_PARTS)


def iter_py_files(paths: list[Path], repo_root: Path) -> list[Path]:
    out: list[Path] = []
    for p in paths:
        if p.is_file() and p.suffix == ".py":
            out.append(p)
        elif p.is_dir():
            for f in sorted(p.rglob("*.py")):
                try:
                    rel = f.resolve().relative_to(repo_root.resolve()).as_posix()
                except ValueError:
                    rel = f.as_posix()
                if not _excluded(rel):
                    out.append(f)
    return out


def build_project(paths: list[Path], repo_root: Path) -> Project:
    project = Project(root=repo_root)
    scanners: list[_FileScanner] = []
    sources: list[tuple[str, str, ast.Module]] = []
    for f in iter_py_files(paths, repo_root):
        try:
            rel = f.resolve().relative_to(repo_root.resolve()).as_posix()
        except ValueError:
            rel = f.as_posix()
        source = f.read_text(encoding="utf-8", errors="replace")
        try:
            tree = ast.parse(source, filename=rel)
        except SyntaxError:
            continue  # dynalint owns syntax-error reporting
        sources.append((rel, source, tree))
    # Pass 1: collect classes + locks first (resolution needs the full
    # class index, and lock-id resolution needs the full lock registry).
    pre = []
    for rel, source, tree in sources:
        for node in ast.walk(tree):
            if isinstance(node, ast.ClassDef):
                project.classes.setdefault(node.name, set()).add(rel)
        pre.append((rel, source, tree))
    for rel, source, tree in pre:
        _collect_locks(rel, tree, project)
    # Pass 2: full scan.
    for rel, source, tree in pre:
        sc = _FileScanner(rel, tree, project)
        sc.visit(tree)
        scanners.append(sc)
        project.trees[rel] = tree
        project.imports_by_file[rel] = sc.imports
        extract_pragmas(rel, source, tree, project)
    resolve_calls(scanners, project)
    return project


def _collect_locks(path: str, tree: ast.Module, project: Project) -> None:
    class_stack: list[str] = []

    def walk(node, in_func: bool):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.ClassDef):
                class_stack.append(child.name)
                walk(child, in_func)
                class_stack.pop()
                continue
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
                walk(child, True)
                continue
            if isinstance(child, ast.Assign) and isinstance(child.value, ast.Call):
                vd = dotted_name(child.value.func)
                if vd in C.LOCK_CONSTRUCTORS:
                    for target in child.targets:
                        td = dotted_name(target)
                        if td is None:
                            continue
                        if td.startswith("self.") and class_stack and "." not in td[5:]:
                            project.locks[(class_stack[-1], td[5:])] = (path, child.lineno)
                        elif "." not in td and not in_func:
                            project.locks[(path, td)] = (path, child.lineno)
            walk(child, in_func)

    walk(tree, False)
