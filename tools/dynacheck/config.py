"""dynacheck configuration: rule tables pinning the generic analyses to
the dynamo-tpu codebase.

Everything here is data. Engine A's rules (``interproc.py``) and the call
graph builder (``callgraph.py``) are generic; this file tells them which
functions are hot paths, which attributes are protocol state, and which
entry points are audited. The blocking-call and lock vocabulary is shared
with dynalint (``tools.dynalint.config``) so the two tiers can never
disagree about what "blocking" or "guarded" means.
"""

from __future__ import annotations

from tools.dynalint import config as L

# ---------------------------------------------------------------------------
# Rule ids (used in pragmas: `# dynacheck: allow-<rule>(<reason>)`)
# ---------------------------------------------------------------------------

RULE_TRANSITIVE_BLOCKING = "transitive-blocking"
RULE_LOCK_ORDER = "lock-order"
RULE_HOLDS_LOCK_UNVERIFIED = "holds-lock-unverified"
RULE_CORO_LEAK = "coroutine-leak"
RULE_CURSOR = "cursor-discipline"
RULE_REGISTRY_DRIFT = "registry-drift"
RULE_WIRE_CONTRACT = "wire-contract"
RULE_LOOP_AFFINITY = "loop-affinity"
RULE_CONFIG_KNOB = "config-knob"

ALL_RULES = (
    RULE_TRANSITIVE_BLOCKING,
    RULE_LOCK_ORDER,
    RULE_HOLDS_LOCK_UNVERIFIED,
    RULE_CORO_LEAK,
    RULE_CURSOR,
    RULE_REGISTRY_DRIFT,
    RULE_WIRE_CONTRACT,
    RULE_LOOP_AFFINITY,
    RULE_CONFIG_KNOB,
)

# ---------------------------------------------------------------------------
# Shared vocabulary (single source of truth: dynalint's config).
# ---------------------------------------------------------------------------

# Step-loop hot paths: {file suffix -> set of function names}. dynalint
# flags DIRECT host-sync calls inside these; dynacheck flags TRANSITIVE
# reachability (a sync two or more frames down the call graph).
HOT_STEP_FUNCS = L.HOT_STEP_FUNCS

# Device->host sync call vocabulary (np.asarray / fetch_replicated /
# .item() / .block_until_ready()).
HOST_SYNC_FNS = L.HOST_SYNC_FNS
HOST_SYNC_METHODS = L.HOST_SYNC_METHODS
HOST_SYNC_ASARRAY_ROOTS = L.HOST_SYNC_ASARRAY_ROOTS

# Event-loop blockers (time.sleep, subprocess.*, requests.*, ...): a hot
# step function transitively reaching one of these is flagged too — the
# step loop runs on a worker thread, but a plan-path sleep serializes
# scheduling exactly like a host sync does.
BLOCKING_CALLS = set(L.BLOCKING_CALLS)
BLOCKING_ROOTS = set(L.BLOCKING_ROOTS)

# The GUARDED_BY registry dynacheck cross-references for drift (satellite:
# the registry is hand-maintained since PR 1; dynacheck fails on entries
# that no longer exist or attrs mutated nowhere under their declared lock).
GUARDED_BY = L.GUARDED_BY
EXTERNAL = L.EXTERNAL

# ---------------------------------------------------------------------------
# lock-order: lock recognition + identity.
# ---------------------------------------------------------------------------

# Constructor call names whose assignment target becomes a known lock:
# `self.X = threading.Lock()` / module-level `_lock = threading.Lock()`.
LOCK_CONSTRUCTORS = {
    "threading.Lock", "threading.RLock", "threading.Condition",
    "asyncio.Lock", "asyncio.Condition", "asyncio.Semaphore",
    "Lock", "RLock",
}

# Attribute-name fallback: a `with <expr>.<attr>:` whose attr ends with
# one of these suffixes is treated as a lock acquisition even when the
# constructor was not seen (e.g. the receiver is another instance).
LOCK_NAME_SUFFIXES = ("lock",)

# ---------------------------------------------------------------------------
# coroutine-leak: calls that take ownership of a coroutine object. A call
# to a project-local `async def` must be awaited, handed to one of these,
# returned, or bound to a name that is used again — anything else is a
# created-but-never-scheduled coroutine silently dropped on the floor
# (the body never runs; Python logs "never awaited" at gc time at best).
# ---------------------------------------------------------------------------

CORO_SINKS = {
    "create_task", "ensure_future", "gather", "wait", "wait_for",
    "shield", "run", "run_until_complete", "run_coroutine_threadsafe",
    "as_completed", "spawn_logged", "timeout", "staggered_race",
}

# ---------------------------------------------------------------------------
# cursor-discipline: the audited-writer registry.
#
# CURSOR_ATTRS maps protocol-state attribute names to a short description
# of the protocol they belong to. ANY write to one of these attributes
# (assign / augassign / del / mutator-method call, on any receiver) in the
# scanned tree is an error unless the enclosing function is listed in
# AUDITED_CURSOR_WRITERS for its file — the commit/rollback/release entry
# points whose bookkeeping the engine-parity tests pin. The three shipped
# cross-function bugs (block-refcount double-release, preemption prompt
# truncation, disagg partial-block misalignment) were all writes to this
# state from paths outside the audited set.
# ---------------------------------------------------------------------------

CURSOR_ATTRS = {
    # Sequence progress cursors (engine/core.py): num_computed_tokens is
    # the `processed` property — the rollback cursor every late-stop /
    # rejected-draft path relies on.
    "processed": "num_computed_tokens cursor",
    "prefilled": "prefill progress cursor",
    "pinned_hashes": "pinned-hash block pins",
    "committed_blocks": "committed-block watermark",
    # Allocator bookkeeping (engine/block_allocator.py and the mocker's
    # hash-only sibling): refcount conservation is the allocator model's
    # core invariant, so host code must not touch these out of band.
    "refcount": "block refcount",
    "_free": "allocator free list",
    "_by_hash": "allocator hash index",
    "_inactive": "allocator inactive LRU",
    "_partials": "allocator partial-block count",
    # Fair-queue DRR state (engine/fair_queue.py, ISSUE 10): deficit
    # balances and the tenant rotation decide admission order; a write
    # from outside the queue's own methods would silently skew fairness.
    "_deficits": "DRR per-tenant deficit balances",
    "_order": "DRR tenant rotation",
    # Cluster-pool global index (llm/kv_pool/global_index.py, ISSUE 11):
    # the per-worker tier ledger IS the routing truth — an out-of-band
    # write would desynchronize it from the radix tree it feeds.
    "_tiers": "global-index per-worker tier ledger",
    # Snapshot-publisher buffer (obs/snapshot.py, ISSUE 13): bounded +
    # ordered like the KV event buffer; an out-of-band write could
    # reorder or unbound the fleet view's feed.
    "_snapbuf": "bounded snapshot-publisher buffer",
    # Degraded-mode discovery state (ISSUE 15): the quarantine buffer
    # (runtime/component.py) and the deferred-removal map
    # (llm/discovery.py) decide what keeps serving through a store
    # blackout — an out-of-band write could drop a live instance mid-
    # outage or resurrect a dead one after it.
    "_quarantine": "lease-expiry delete quarantine",
    "_deferred": "deferred model-removal map",
}

# {file suffix -> set of audited writer qualnames}. Nested defs are dotted
# (`EngineCore._plan_megastep.commit` is the megastep commit closure).
AUDITED_CURSOR_WRITERS: dict[str, set[str]] = {
    "dynamo_tpu/engine/core.py": {
        # admission (prefix-cache pins + cached-cursor fast-forward)
        "EngineCore._admit",
        # block commit path (shared by every scheduler)
        "EngineCore._commit_completed",
        # prefill-chunk cursor advance (wave + mixed steps)
        "EngineCore._advance_prefill_chunk",
        # ring-prefill synchronous commit
        "EngineCore._run_ring_prefill",
        # rollback entry points
        "EngineCore._preempt",
        "EngineCore._release_blocks",
        # per-step commit closures / helpers
        "EngineCore._plan_prefill_wave.commit",
        "EngineCore._plan_megastep.commit",
        "EngineCore._plan_mixed.commit",
        # Universal megastep (ISSUE 12): the fused mixed/verify commit
        # closure applies the same cursor algebra — accept-length
        # replay, chunk advance, scanned-continuation rollback.
        "EngineCore._plan_fused.commit",
        "EngineCore._apply_verify_row",
    },
    # The allocator owns its bookkeeping wholesale: every public method is
    # an audited entry point; the rule guards against OTHER files reaching
    # into `allocator._free` / `blk.refcount` directly.
    "dynamo_tpu/engine/block_allocator.py": {
        "DeviceBlockAllocator.__init__",
        "DeviceBlockAllocator._evict_lru",
        "DeviceBlockAllocator.alloc",
        "DeviceBlockAllocator.alloc_many",
        "DeviceBlockAllocator.alloc_for_import",
        "DeviceBlockAllocator.acquire_cached",
        "DeviceBlockAllocator.commit",
        "DeviceBlockAllocator.free_partial",
        "DeviceBlockAllocator.release",
        "DeviceBlockAllocator.register_inactive",
        "DeviceBlockAllocator.clear_cache",
    },
    # The fair queue owns its DRR bookkeeping wholesale (every mutator
    # is an entry point); the rule guards against OTHER files reaching
    # into `waiting._deficits` / `waiting._order` directly.
    "dynamo_tpu/engine/fair_queue.py": {
        "FairQueue.__init__",
        "FairQueue._queue_for",
        "FairQueue.append",
        "FairQueue.appendleft",
        "FairQueue.head",
        "FairQueue.pop",
        "FairQueue._drop_tenant",
        "FairQueue.remove",
        "FairQueue.sweep",
    },
    # The mocker mirrors the scheduler on its virtual clock; its step loop
    # and hash-only KV manager are the same protocol in miniature.
    "dynamo_tpu/llm/mocker/engine.py": {
        "MockTpuEngine._admit",
        "MockTpuEngine._step",
    },
    "dynamo_tpu/llm/mocker/kv_manager.py": {
        "MockKvManager.__init__",
        "MockKvManager._evict_lru",
        "MockKvManager._ensure_headroom",
        "MockKvManager.acquire_cached",
        "MockKvManager.allocate_partial",
        "MockKvManager.commit_block",
        "MockKvManager.release_partial",
        "MockKvManager.release",
        "MockKvManager.clear_unpinned",
        "MockKvManager.clear",
        # Cluster-pool import (ISSUE 11): register_inactive's mocker twin.
        "MockKvManager.import_block",
    },
    # The snapshot publisher owns its bounded buffer (tick task enqueues,
    # one drain task pops — both loop-affine); the rule guards OTHER
    # files reaching into `pub._snapbuf`.
    "dynamo_tpu/obs/snapshot.py": {
        "SnapshotPublisher.publish_nowait",
        "SnapshotPublisher._drain",
    },
    # Degraded-mode discovery (ISSUE 15): the endpoint client owns its
    # quarantine buffer (watch loop + sweep + reconnect reconcile, all
    # loop-affine); the rule guards OTHER files reaching into
    # `client._quarantine`.
    "dynamo_tpu/runtime/component.py": {
        "EndpointClient.__init__",
        "EndpointClient._watch_loop",
        "EndpointClient._remove_instance",
        "EndpointClient._sweep_quarantine",
        "EndpointClient._reconcile",
    },
    # Same ownership shape for the model watcher's deferred-removal map.
    "dynamo_tpu/llm/discovery.py": {
        "ModelWatcher.__init__",
        "ModelWatcher._on_put",
        "ModelWatcher._on_delete",
        "ModelWatcher._sweep_deferred",
    },
    # The global index owns its tier ledger wholesale (single event-task
    # writer); the rule guards OTHER files reaching into `idx._tiers`.
    "dynamo_tpu/llm/kv_pool/global_index.py": {
        "GlobalKvIndex.__init__",
        "GlobalKvIndex._apply_stored",
        "GlobalKvIndex._apply_removed",
        "GlobalKvIndex._retire",
        "GlobalKvIndex.remove_worker",
    },
}

# ---------------------------------------------------------------------------
# wire-contract: the per-plane frame-key schema lives in
# dynamo_tpu/runtime/wire.py (SCHEMAS / CONTEXTS / VALUES); the rule
# parses that file STATICALLY — Engine A never imports product code.
# WIRE_PLANE_FILES registers which scanned files speak which planes;
# production/consumption is accounted per plane across its files.
# ---------------------------------------------------------------------------

WIRE_SCHEMA_FILE = "dynamo_tpu/runtime/wire.py"

# {file suffix -> planes spoken}. A file's wire.* references must belong
# to one of its planes; raw string-literal keys at send sites matching a
# plane key are backslide findings.
WIRE_PLANE_FILES: dict[str, tuple[str, ...]] = {
    "dynamo_tpu/runtime/dataplane.py": ("dataplane",),
    "dynamo_tpu/runtime/store/client.py": ("store", "store.event"),
    "dynamo_tpu/runtime/store/server.py": ("store", "store.event"),
    "dynamo_tpu/runtime/component.py": ("instance", "store.event"),
    "dynamo_tpu/llm/discovery.py": ("store.event",),
    "dynamo_tpu/obs/snapshot.py": ("snapshot",),
    "dynamo_tpu/llm/kv_pool/peer_client.py": ("kvstream", "kvimport"),
    "dynamo_tpu/backends/jax/main.py": ("kvstream", "kvimport"),
    "dynamo_tpu/backends/mocker/main.py": ("kvstream",),
    "dynamo_tpu/engine/core.py": ("kvimport",),
}

# Call names whose dict-literal arguments are frame SEND sites: a raw
# string key there (in a registered plane file, matching a plane key)
# is a backslide to the pre-registry idiom. Directly-yielded dict
# literals in plane files are send sites too (streaming handlers).
WIRE_SEND_FNS = {"pack", "send_frame", "write_frame", "push"}

# Functions producing store-plane keys through KWARG names (the
# ``_request(op, k=..., v=...)`` splice): each keyword name at a call to
# one of these is a produced key for the file's planes.
WIRE_KWARG_PRODUCERS = {"_request"}

# ---------------------------------------------------------------------------
# loop-affinity: state the EXTERNAL/loop-affine convention declares
# single-loop-owned. {file suffix -> {(class, attr): description}}. The
# rule flags any write to one of these reachable (over the call graph)
# from a thread entry point (to_thread / run_in_executor / submit /
# Thread(target=...)).
# ---------------------------------------------------------------------------

LOOP_AFFINE: dict[str, dict[tuple[str, str], str]] = {
    "dynamo_tpu/obs/snapshot.py": {
        ("SnapshotPublisher", "_snapbuf"): "bounded snapshot buffer",
    },
    "dynamo_tpu/llm/kv_router/publisher.py": {
        ("KvEventPublisher", "_buf"): "KV event buffer",
    },
    "dynamo_tpu/runtime/component.py": {
        ("EndpointClient", "_quarantine"): "lease-expiry quarantine map",
    },
    "dynamo_tpu/llm/discovery.py": {
        ("ModelWatcher", "_deferred"): "deferred model-removal map",
    },
    "dynamo_tpu/llm/kv_pool/global_index.py": {
        ("GlobalKvIndex", "_tiers"): "per-worker tier ledger",
        ("GlobalKvIndex", "_last_event_id"): "per-worker event cursor",
        ("GlobalKvIndex", "_fwd_id"): "forwarded-event id counter",
    },
}

# Thread entry vocabulary (callgraph records the spawned callable at
# these sites): asyncio.to_thread(fn), loop.run_in_executor(None, fn),
# executor.submit(fn), threading.Thread(target=fn).
THREAD_SPAWNERS = {"to_thread", "run_in_executor", "submit", "Thread"}

# ---------------------------------------------------------------------------
# config-knob: the central registry lives in dynamo_tpu/knobs.py (KNOBS /
# PREFIXES); the rule parses it statically, collects every env read in
# the tree (os.environ / os.getenv / knobs.* accessors / wrapper
# functions whose body reads the env through a parameter), resolves
# dynamically-built names through module constants and parameter
# defaults, and fails undocumented, unused, duplicate-default, and
# unresolvable reads. `# dynacheck: knob-dynamic(<reason>)` escapes a
# genuinely dynamic name.
# ---------------------------------------------------------------------------

KNOB_REGISTRY_FILE = "dynamo_tpu/knobs.py"
KNOB_DOC_FILE = "README.md"

# Accessor functions on the knobs module (arg 0 is the knob name).
KNOB_ACCESSORS = {
    "raw", "get", "get_str", "get_int", "get_float", "get_bool", "default",
}

# ---------------------------------------------------------------------------
# File selection.
# ---------------------------------------------------------------------------

# Default scan root for the tree run (`python -m tools.dynacheck`).
DEFAULT_PATHS = ("dynamo_tpu",)

# Shared with dynalint (live alias, not a copy): the two tiers must
# scan the same file set, and the dynacheck cache key depends on it.
EXCLUDE_PARTS = L.EXCLUDE_PARTS

# ---------------------------------------------------------------------------
# Engine B exploration bounds. Depths are chosen so the full tree run
# stays well under the CI runtime budget (< 60 s) while every model still
# visits its complete reachable state space (the explorers report when the
# frontier is exhausted before the bound — all three are, at these bounds).
# ---------------------------------------------------------------------------

MODEL_DEPTHS = {
    "allocator": 18,
    "cursor": 12,
    "pp-wavefront": 12,
    "breaker": 18,
    "quarantine": 20,
    "keepalive": 12,
    "planner": 16,
}
