"""Engine A v2 rules: the PR 16 contract analyses.

7. ``wire-contract`` — the per-plane frame-key registry
   (``dynamo_tpu/runtime/wire.py``) is parsed STATICALLY; every
   ``wire.<CONST>`` reference in a registered plane file is classified as
   produced (dict-literal key, subscript store, ``_request`` kwarg) or
   consumed (subscript load, ``.get``/``.pop``/``.setdefault``,
   ``in``-test). A key produced but never consumed, consumed but never
   produced, reused across planes sharing a parse context with
   conflicting meaning, or written as a raw string literal at a send
   site, is drift.
8. ``loop-affinity`` — state in the ``LOOP_AFFINE`` registry is owned by
   one event loop; any write reachable over the call graph from a thread
   entry point (``to_thread`` / ``run_in_executor`` / ``submit`` /
   ``Thread(target=...)``) is a cross-loop race.
9. ``config-knob`` — every env read in the tree must resolve into the
   central knob registry (``dynamo_tpu/knobs.py``): direct ``os.environ``
   reads of a registered prefix outside the registry are bypasses,
   accessor/wrapper reads of unregistered names are failures, literal
   defaults at call sites duplicate the registry's single default,
   registered knobs nobody reads are dead, and registered knobs missing
   from the README are undocumented. Dynamically-built names resolve
   through module constants and parameter defaults; true escapes carry
   ``# dynacheck: knob-dynamic(<reason>)``.

Like the rest of Engine A these under-approximate: an unresolvable
construct stays silent rather than spamming.
"""

from __future__ import annotations

import ast
import re

from tools.dynacheck import config as C
from tools.dynacheck.callgraph import FuncInfo, Project, _module_path, dotted_name
from tools.dynacheck.interproc import Finding

_CONSUME_METHODS = {"get", "pop", "setdefault"}


def _tree_scan(project: Project) -> bool:
    return any(p.startswith("dynamo_tpu/") for p in project.trees)


def _match_file(project: Project, suffix: str) -> str | None:
    for p in project.trees:
        if p.endswith(suffix):
            return p
    return None


def _parents(tree: ast.Module) -> dict[ast.AST, ast.AST]:
    out: dict[ast.AST, ast.AST] = {}
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            out[child] = node
    return out


def _module_aliases(project: Project, path: str, target_suffix: str) -> set[str]:
    """Local names in ``path`` bound to the module whose repo-relative
    path ends with ``target_suffix`` (e.g. the wire or knobs module)."""
    out: set[str] = set()
    for name, dotted in project.imports_by_file.get(path, {}).items():
        mpath = _module_path(dotted, project.root)
        if mpath is not None and mpath.endswith(target_suffix):
            out.add(name)
    return out


# ---------------------------------------------------------------------------
# Rule 7: wire-contract
# ---------------------------------------------------------------------------


class _WireSchema:
    def __init__(self) -> None:
        self.consts: dict[str, str] = {}       # CONST name -> key string
        self.schemas: dict[str, dict[str, str]] = {}  # plane -> {CONST: meaning}
        self.contexts: dict[str, str] = {}     # plane -> parse context tag
        self.values: set[str] = set()          # discriminator VALUE consts
        self.path = ""

    def plane_keys(self, plane: str) -> dict[str, str]:
        """{key string -> CONST name} for one plane."""
        return {
            self.consts[c]: c
            for c in self.schemas.get(plane, ())
            if c in self.consts
        }


def _load_wire_schema(project: Project) -> tuple[_WireSchema | None, list[Finding]]:
    path = _match_file(project, C.WIRE_SCHEMA_FILE)
    if path is None:
        if _tree_scan(project):
            return None, [Finding(
                C.WIRE_SCHEMA_FILE, 0, C.RULE_WIRE_CONTRACT,
                "wire schema module is registered but not in the scanned "
                "tree: the module moved or was deleted — update "
                "tools/dynacheck/config.py WIRE_SCHEMA_FILE",
            )]
        return None, []
    ws = _WireSchema()
    ws.path = path
    tree = project.trees[path]
    findings: list[Finding] = []
    for node in tree.body:
        targets: list[ast.expr] = []
        value: ast.expr | None = None
        if isinstance(node, ast.Assign):
            targets, value = node.targets, node.value
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets, value = [node.target], node.value
        for t in targets:
            if not isinstance(t, ast.Name):
                continue
            if (
                t.id.isupper()
                and isinstance(value, ast.Constant)
                and isinstance(value.value, str)
            ):
                ws.consts[t.id] = value.value
            elif t.id in ("SCHEMAS", "CONTEXTS", "VALUES") and isinstance(
                value, ast.Dict
            ):
                try:
                    table = ast.literal_eval(value)
                except ValueError:
                    findings.append(Finding(
                        path, node.lineno, C.RULE_WIRE_CONTRACT,
                        f"{t.id} must be a pure dict literal so the "
                        "checker can read it statically",
                    ))
                    continue
                if t.id == "SCHEMAS":
                    ws.schemas = table
                elif t.id == "CONTEXTS":
                    ws.contexts = table
                else:
                    ws.values = set(table)
    # Registry self-consistency (the static twin of wire._self_check).
    registered = {c for s in ws.schemas.values() for c in s} | ws.values
    for plane, schema in sorted(ws.schemas.items()):
        if plane not in ws.contexts:
            findings.append(Finding(
                path, 0, C.RULE_WIRE_CONTRACT,
                f"plane {plane!r} has no parse context in CONTEXTS",
            ))
        for const in sorted(schema):
            if const not in ws.consts:
                findings.append(Finding(
                    path, 0, C.RULE_WIRE_CONTRACT,
                    f"SCHEMAS[{plane!r}] names {const}, which is not a "
                    "str constant in the wire module",
                ))
    for name in sorted(ws.consts):
        if name not in registered:
            findings.append(Finding(
                path, 0, C.RULE_WIRE_CONTRACT,
                f"wire constant {name} is not registered in SCHEMAS or "
                "VALUES",
            ))
    # Cross-plane conflicts: same parse context + same key string +
    # different meaning is ambiguous for every reader of that context.
    by_ctx_key: dict[tuple[str, str], list[tuple[str, str, str]]] = {}
    for plane, schema in ws.schemas.items():
        ctx = ws.contexts.get(plane, plane)
        for const, meaning in schema.items():
            key = ws.consts.get(const)
            if key is not None:
                by_ctx_key.setdefault((ctx, key), []).append(
                    (plane, const, meaning)
                )
    for (ctx, key), uses in sorted(by_ctx_key.items()):
        if len({m for _, _, m in uses}) > 1:
            detail = "; ".join(
                f"{plane}.{const} = {meaning!r}"
                for plane, const, meaning in sorted(uses)
            )
            findings.append(Finding(
                path, 0, C.RULE_WIRE_CONTRACT,
                f"key {key!r} is reused with conflicting meaning inside "
                f"parse context {ctx!r} ({detail}): a reader of this "
                "context cannot tell the two apart — split the planes "
                "into different contexts or rename a key",
            ))
    return ws, findings


def check_wire_contract(project: Project) -> list[Finding]:
    ws, findings = _load_wire_schema(project)
    if ws is None:
        return findings
    # site accounting: (plane, CONST) -> [(path, line)]
    produced: dict[tuple[str, str], list[tuple[str, int]]] = {}
    consumed: dict[tuple[str, str], list[tuple[str, int]]] = {}
    files_by_plane: dict[str, list[str]] = {}
    registered_present: dict[str, tuple[str, ...]] = {}
    for suffix, planes in C.WIRE_PLANE_FILES.items():
        path = _match_file(project, suffix)
        if path is None:
            continue
        registered_present[path] = planes
        for plane in planes:
            files_by_plane.setdefault(plane, []).append(path)

    for path, planes in sorted(registered_present.items()):
        tree = project.trees[path]
        parents = _parents(tree)
        aliases = _module_aliases(project, path, C.WIRE_SCHEMA_FILE)
        # key string -> (plane, CONST) for this file's planes (first
        # plane claiming a key wins; same-file planes never collide in
        # practice because their contexts differ).
        file_keys: dict[str, tuple[str, str]] = {}
        for plane in planes:
            for key, const in ws.plane_keys(plane).items():
                file_keys.setdefault(key, (plane, const))
        for node in ast.walk(tree):
            if isinstance(node, ast.Attribute) and (
                isinstance(node.value, ast.Name) and node.value.id in aliases
            ):
                const = node.attr
                if const in ws.values or const not in ws.consts:
                    continue
                plane = next(
                    (p for p in planes if const in ws.schemas.get(p, ())), None
                )
                if plane is None:
                    owners = sorted(
                        p for p, s in ws.schemas.items() if const in s
                    )
                    if owners and not project.suppressed(
                        C.RULE_WIRE_CONTRACT, path, node.lineno
                    ):
                        findings.append(Finding(
                            path, node.lineno, C.RULE_WIRE_CONTRACT,
                            f"{path} references {const} of plane "
                            f"{owners[0]!r}, but is not registered for it "
                            "— add the plane in tools/dynacheck/config.py "
                            "WIRE_PLANE_FILES or use the right schema",
                        ))
                    continue
                cls = _classify_ref(node, parents)
                site = (path, node.lineno)
                if cls == "produced":
                    produced.setdefault((plane, const), []).append(site)
                elif cls == "consumed":
                    consumed.setdefault((plane, const), []).append(site)
            elif isinstance(node, ast.Call):
                # _request(op, k=..., v=...) splice: kwarg names are
                # produced store keys.
                name = (
                    node.func.attr if isinstance(node.func, ast.Attribute)
                    else node.func.id if isinstance(node.func, ast.Name)
                    else None
                )
                if name in C.WIRE_KWARG_PRODUCERS:
                    for kw in node.keywords:
                        if kw.arg and kw.arg in file_keys:
                            plane, const = file_keys[kw.arg]
                            produced.setdefault((plane, const), []).append(
                                (path, node.lineno)
                            )
        # Backslide scan: raw string keys at send sites.
        findings.extend(_raw_literal_sends(project, path, tree, parents, file_keys))

    # Pairing: only judged for planes whose full registered file set was
    # scanned — a narrow scan proves nothing about the other side.
    complete = {
        plane for plane, suffixes in _plane_suffixes().items()
        if all(_match_file(project, sfx) is not None for sfx in suffixes)
        and plane in files_by_plane
    }
    for plane in sorted(complete):
        for const in sorted(ws.schemas.get(plane, ())):
            if const not in ws.consts:
                continue
            prod = produced.get((plane, const), [])
            cons = consumed.get((plane, const), [])
            if prod and not cons:
                path, line = min(prod)
                if not project.suppressed(C.RULE_WIRE_CONTRACT, path, line):
                    findings.append(Finding(
                        path, line, C.RULE_WIRE_CONTRACT,
                        f"wire key {const} ({ws.consts[const]!r}, plane "
                        f"{plane}) is produced here but consumed nowhere "
                        "in the plane's files: dead weight on the wire, "
                        "or the consumer forgot to parse it",
                    ))
            elif cons and not prod:
                path, line = min(cons)
                if not project.suppressed(C.RULE_WIRE_CONTRACT, path, line):
                    findings.append(Finding(
                        path, line, C.RULE_WIRE_CONTRACT,
                        f"wire key {const} ({ws.consts[const]!r}, plane "
                        f"{plane}) is consumed here but produced nowhere "
                        "in the plane's files: this branch can never "
                        "fire, or the producer forgot to send it",
                    ))
            elif not prod and not cons:
                findings.append(Finding(
                    ws.path, 0, C.RULE_WIRE_CONTRACT,
                    f"wire key {const} ({ws.consts[const]!r}, plane "
                    f"{plane}) is registered but neither produced nor "
                    "consumed anywhere: drop it from the schema",
                ))
    return findings


def _plane_suffixes() -> dict[str, list[str]]:
    out: dict[str, list[str]] = {}
    for suffix, planes in C.WIRE_PLANE_FILES.items():
        for plane in planes:
            out.setdefault(plane, []).append(suffix)
    return out


def _classify_ref(node: ast.Attribute, parents: dict) -> str | None:
    parent = parents.get(node)
    if isinstance(parent, ast.Dict) and node in parent.keys:
        return "produced"
    if isinstance(parent, ast.Subscript) and parent.slice is node:
        if isinstance(parent.ctx, ast.Store):
            return "produced"
        return "consumed"
    if (
        isinstance(parent, ast.Call)
        and parent.args
        and parent.args[0] is node
        and isinstance(parent.func, ast.Attribute)
        and parent.func.attr in _CONSUME_METHODS
    ):
        return "consumed"
    if isinstance(parent, ast.Compare) and parent.left is node and any(
        isinstance(op, (ast.In, ast.NotIn)) for op in parent.ops
    ):
        return "consumed"
    return None  # neutral reference (default value, comparison operand, ...)


def _raw_literal_sends(
    project: Project, path: str, tree: ast.Module, parents: dict,
    file_keys: dict[str, tuple[str, str]],
) -> list[Finding]:
    out: list[Finding] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Dict):
            continue
        parent = parents.get(node)
        send_site = False
        if isinstance(parent, (ast.Yield, ast.YieldFrom)):
            send_site = True
        elif isinstance(parent, ast.Call) and node in parent.args:
            name = (
                parent.func.attr if isinstance(parent.func, ast.Attribute)
                else parent.func.id if isinstance(parent.func, ast.Name)
                else None
            )
            send_site = name in C.WIRE_SEND_FNS
        if not send_site:
            continue
        for key in node.keys:
            if (
                isinstance(key, ast.Constant)
                and isinstance(key.value, str)
                and key.value in file_keys
            ):
                if project.suppressed(C.RULE_WIRE_CONTRACT, path, key.lineno):
                    continue
                plane, const = file_keys[key.value]
                out.append(Finding(
                    path, key.lineno, C.RULE_WIRE_CONTRACT,
                    f"raw string literal {key.value!r} used as a frame "
                    f"key at a send site: use wire.{const} (plane "
                    f"{plane}) so the contract stays checkable",
                ))
    return out


# ---------------------------------------------------------------------------
# Rule 8: loop-affinity
# ---------------------------------------------------------------------------


def check_loop_affinity(project: Project) -> list[Finding]:
    # Resolve the registry against the scanned tree.
    affine: dict[tuple[str, str], tuple[str, str]] = {}  # (class, attr) -> (path, desc)
    attr_names: dict[str, tuple[str, str]] = {}          # attr -> (class, desc)
    findings: list[Finding] = []
    tree_scan = _tree_scan(project)
    for suffix, entries in sorted(C.LOOP_AFFINE.items()):
        path = _match_file(project, suffix)
        if path is None:
            if tree_scan and suffix.startswith("dynamo_tpu/"):
                findings.append(Finding(
                    suffix, 0, C.RULE_LOOP_AFFINITY,
                    f"LOOP_AFFINE registers {suffix} but no scanned file "
                    "matches it — update tools/dynacheck/config.py",
                ))
            continue
        for (cls, attr), desc in sorted(entries.items()):
            if path not in project.classes.get(cls, set()):
                findings.append(Finding(
                    path, 0, C.RULE_LOOP_AFFINITY,
                    f"LOOP_AFFINE entry ({cls}, {attr}): class {cls} no "
                    f"longer exists in {path}",
                ))
                continue
            affine[(cls, attr)] = (path, desc)
            attr_names[attr] = (cls, desc)
    if not affine:
        return findings

    # BFS from every thread-spawned callable; keep one spawn witness per
    # reached function for the message.
    origin: dict[str, tuple[str, str, int]] = {}  # func key -> (root qual, path, line)
    frontier: list[str] = []
    for f in sorted(project.functions.values(), key=lambda fi: fi.key):
        for cs in f.spawn_sites:
            for t in sorted(cs.targets):
                if t not in origin:
                    tinfo = project.functions.get(t)
                    if tinfo is None:
                        continue
                    origin[t] = (tinfo.qualname, f.path, cs.line)
                    frontier.append(t)
    while frontier:
        nxt: list[str] = []
        for key in frontier:
            info = project.functions.get(key)
            if info is None:
                continue
            for cs in info.calls:
                for t in sorted(cs.targets):
                    if t not in origin:
                        origin[t] = origin[key]
                        nxt.append(t)
        frontier = nxt

    for key in sorted(origin):
        info = project.functions.get(key)
        if info is None:
            continue
        cls = (
            info.qualname.split(".")[0]
            if "." in info.qualname
            and info.qualname.split(".")[0] in project.classes
            else None
        )
        root_qual, spawn_path, spawn_line = origin[key]
        for w in info.writes:
            hit: tuple[str, str] | None = None  # (class, desc)
            if (
                cls is not None
                and (cls, w.attr) in affine
                and affine[(cls, w.attr)][0] == info.path
                and w.receiver in ("self", "self(alias)")
            ):
                hit = (cls, affine[(cls, w.attr)][1])
            elif (
                w.attr in attr_names
                and w.receiver not in ("self", "self(alias)", "<local>", "<global>")
            ):
                # Foreign receiver (`pub._snapbuf.append(...)`) from a
                # thread context: same race, reached from outside.
                hit = attr_names[w.attr]
            if hit is None:
                continue
            if project.suppressed(C.RULE_LOOP_AFFINITY, info.path, w.line):
                continue
            owner_cls, desc = hit
            findings.append(Finding(
                info.path, w.line, C.RULE_LOOP_AFFINITY,
                f"{info.qualname} writes {owner_cls}.{w.attr} ({desc}), "
                "which is loop-affine, but is reachable from thread "
                f"entry point {root_qual!r} (spawned at "
                f"{spawn_path}:{spawn_line}): a cross-loop write races "
                "the owning event loop — marshal through "
                "call_soon_threadsafe or keep the touch on the loop",
            ))
    findings.sort(key=lambda f: (f.path, f.line, f.message))
    return findings


# ---------------------------------------------------------------------------
# Rule 9: config-knob
# ---------------------------------------------------------------------------

def _doc_token_re(prefixes: tuple[str, ...]) -> re.Pattern[str]:
    alts = "|".join(re.escape(p) for p in prefixes)
    return re.compile(r"\b(?:" + alts + r")[A-Z0-9_]*[A-Z0-9]\b")


class _KnobRegistry:
    def __init__(self) -> None:
        self.path = ""
        self.prefixes: tuple[str, ...] = ()
        self.knobs: dict[str, int] = {}      # name -> registration line
        self.defaults: dict[str, object] = {}  # name -> literal default


def _load_knob_registry(project: Project) -> tuple[_KnobRegistry | None, list[Finding]]:
    path = _match_file(project, C.KNOB_REGISTRY_FILE)
    if path is None:
        if _tree_scan(project):
            return None, [Finding(
                C.KNOB_REGISTRY_FILE, 0, C.RULE_CONFIG_KNOB,
                "knob registry module is registered but not in the "
                "scanned tree — update tools/dynacheck/config.py "
                "KNOB_REGISTRY_FILE",
            )]
        return None, []
    reg = _KnobRegistry()
    reg.path = path
    tree = project.trees[path]
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name) and t.id == "PREFIXES":
                    try:
                        reg.prefixes = tuple(ast.literal_eval(node.value))
                    except ValueError:
                        pass
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Name)
            and node.func.id == "Knob"
            and node.args
            and isinstance(node.args[0], ast.Constant)
            and isinstance(node.args[0].value, str)
        ):
            name = node.args[0].value
            reg.knobs[name] = node.lineno
            if len(node.args) > 1 and isinstance(node.args[1], ast.Constant):
                reg.defaults[name] = node.args[1].value
    if not reg.prefixes:
        reg.prefixes = ("DYN_", "DYNAMO_TPU_")
    return reg, []


def _body_skip_nested(nodes: list[ast.AST]):
    """Walk statements without descending into nested defs (each nested
    def is its own FuncInfo and walks itself)."""
    stack = list(nodes)
    while stack:
        n = stack.pop()
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            continue
        yield n
        stack.extend(ast.iter_child_nodes(n))


def _resolve_name_expr(
    expr: ast.expr | None,
    module_consts: dict[str, str],
    param_defaults: dict[str, ast.expr],
) -> str | None:
    if expr is None:
        return None
    if isinstance(expr, ast.Constant) and isinstance(expr.value, str):
        return expr.value
    if isinstance(expr, ast.Name):
        if expr.id in module_consts:
            return module_consts[expr.id]
        default = param_defaults.get(expr.id)
        if default is not None:
            return _resolve_name_expr(default, module_consts, {})
        return None
    if isinstance(expr, ast.BinOp) and isinstance(expr.op, ast.Add):
        left = _resolve_name_expr(expr.left, module_consts, param_defaults)
        right = _resolve_name_expr(expr.right, module_consts, param_defaults)
        if left is not None and right is not None:
            return left + right
    if isinstance(expr, ast.JoinedStr):
        parts: list[str] = []
        for v in expr.values:
            if isinstance(v, ast.Constant) and isinstance(v.value, str):
                parts.append(v.value)
            elif isinstance(v, ast.FormattedValue):
                inner = _resolve_name_expr(v.value, module_consts, param_defaults)
                if inner is None:
                    return None
                parts.append(inner)
        return "".join(parts)
    return None


def _env_read_site(node: ast.AST, os_aliases: set[str]):
    """(name_expr, default_expr) when ``node`` reads the environment via
    os.environ.get / os.getenv / os.environ[...]; else None."""
    if isinstance(node, ast.Call):
        d = dotted_name(node.func)
        if d is None:
            return None
        head, _, rest = d.partition(".")
        if head not in os_aliases:
            return None
        if rest in ("environ.get", "getenv"):
            name = node.args[0] if node.args else None
            default = node.args[1] if len(node.args) > 1 else None
            return (name, default)
        return None
    if (
        isinstance(node, ast.Subscript)
        and isinstance(node.ctx, ast.Load)
        and dotted_name(node.value) is not None
    ):
        d = dotted_name(node.value)
        head, _, rest = d.partition(".")
        if head in os_aliases and rest == "environ":
            return (node.slice, None)
    return None


def check_config_knobs(project: Project) -> list[Finding]:
    reg, findings = _load_knob_registry(project)
    if reg is None:
        return findings
    # Absence-based checks (knob never read / never documented) only mean
    # something when the registry was scanned alongside the code that
    # would read it — a lone-file scan proves nothing about "nowhere".
    global_checks = len(project.trees) > 1

    # Per-file context tables.
    module_consts: dict[str, dict[str, str]] = {}
    for path, tree in project.trees.items():
        consts: dict[str, str] = {}
        for node in tree.body:
            if isinstance(node, ast.Assign) and isinstance(node.value, ast.Constant):
                if isinstance(node.value.value, str):
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            consts[t.id] = node.value.value
        module_consts[path] = consts

    def os_aliases(path: str) -> set[str]:
        return {
            name
            for name, dotted in project.imports_by_file.get(path, {}).items()
            if dotted == "os" or dotted.startswith("os.")
        } | ({"os"} if "os" not in project.imports_by_file.get(path, {}) else set())

    def param_defaults_of(f: FuncInfo | None) -> dict[str, ast.expr]:
        if f is None or f.node is None:
            return {}
        node = f.node
        args = node.args
        out: dict[str, ast.expr] = {}
        pos = args.posonlyargs + args.args
        for arg, default in zip(pos[len(pos) - len(args.defaults):], args.defaults):
            out[arg.arg] = default
        for arg, default in zip(args.kwonlyargs, args.kw_defaults):
            if default is not None:
                out[arg.arg] = default
        return out

    # Pass 1: wrapper discovery — a function whose body reads the env
    # through one of its own parameters is an accessor in disguise; its
    # CALL SITES carry the knob names.
    wrappers: dict[tuple[str, str], int] = {}  # (path, func name) -> name param index
    for f in project.functions.values():
        if f.node is None or f.path == reg.path:
            continue
        params = [
            a.arg for a in f.node.args.posonlyargs + f.node.args.args
        ]
        for node in _body_skip_nested(f.node.body):
            site = _env_read_site(node, os_aliases(f.path))
            if site is None:
                continue
            name_expr, _default = site
            if isinstance(name_expr, ast.Name) and name_expr.id in params:
                wrappers[(f.path, f.name)] = params.index(name_expr.id)

    knob_aliases: dict[str, set[str]] = {
        path: _module_aliases(project, path, C.KNOB_REGISTRY_FILE)
        for path in project.trees
    }

    reads: dict[str, list[tuple[str, int]]] = {}  # knob name -> sites

    def record_read(name: str, path: str, line: int, *, registry_required: bool) -> None:
        reads.setdefault(name, []).append((path, line))
        if name not in reg.knobs:
            if registry_required or name.startswith(reg.prefixes):
                if not project.suppressed(C.RULE_CONFIG_KNOB, path, line):
                    findings.append(Finding(
                        path, line, C.RULE_CONFIG_KNOB,
                        f"env knob {name!r} is read here but not "
                        f"registered in {C.KNOB_REGISTRY_FILE}: register "
                        "it (one default, one doc line) so the table "
                        "stays the single source of truth",
                    ))

    def unresolved(path: str, line: int, via: str) -> None:
        if project.suppressed(C.RULE_CONFIG_KNOB, path, line):
            return
        findings.append(Finding(
            path, line, C.RULE_CONFIG_KNOB,
            f"env read via {via} with a dynamically-built name the "
            "checker cannot resolve: route it through a module constant "
            "or mark it `# dynacheck: knob-dynamic(<reason>)`",
        ))

    def scan_region(
        path: str, nodes: list[ast.AST], f: FuncInfo | None
    ) -> None:
        consts = module_consts.get(path, {})
        defaults = param_defaults_of(f)
        oa = os_aliases(path)
        ka = knob_aliases.get(path, set())
        for node in _body_skip_nested(nodes):
            site = _env_read_site(node, oa) if path != reg.path else None
            if site is not None:
                name_expr, default_expr = site
                if (
                    isinstance(name_expr, ast.Name)
                    and f is not None
                    and f.node is not None
                    and name_expr.id in {
                        a.arg for a in f.node.args.posonlyargs + f.node.args.args
                    }
                ):
                    continue  # wrapper internals: call sites are checked
                name = _resolve_name_expr(name_expr, consts, defaults)
                line = getattr(node, "lineno", 0)
                if name is None:
                    unresolved(path, line, "os.environ")
                    continue
                if not name.startswith(reg.prefixes):
                    continue  # foreign env (JAX_PLATFORMS, TMPDIR, ...)
                record_read(name, path, line, registry_required=False)
                if path != reg.path and not project.suppressed(
                    C.RULE_CONFIG_KNOB, path, line
                ):
                    findings.append(Finding(
                        path, line, C.RULE_CONFIG_KNOB,
                        f"direct os.environ read of {name!r} bypasses "
                        "the registry: read it through dynamo_tpu.knobs "
                        "so the default lives in exactly one place",
                    ))
                continue
            if not isinstance(node, ast.Call):
                continue
            line = node.lineno
            func = node.func
            # knobs.get_*/raw/default("NAME")
            if (
                isinstance(func, ast.Attribute)
                and func.attr in C.KNOB_ACCESSORS
                and isinstance(func.value, ast.Name)
                and func.value.id in ka
            ):
                name = _resolve_name_expr(
                    node.args[0] if node.args else None, consts, defaults
                )
                if name is None:
                    unresolved(path, line, f"knobs.{func.attr}")
                else:
                    record_read(name, path, line, registry_required=True)
                continue
            # wrapper call sites: _env("DYN_X", cfg.field)
            wname = (
                func.attr if isinstance(func, ast.Attribute)
                else func.id if isinstance(func, ast.Name) else None
            )
            if wname is not None and (path, wname) in wrappers:
                idx = wrappers[(path, wname)]
                arg = node.args[idx] if len(node.args) > idx else None
                name = _resolve_name_expr(arg, consts, defaults)
                if name is None:
                    unresolved(path, line, f"{wname}()")
                    continue
                record_read(name, path, line, registry_required=True)
                for j, other in enumerate(node.args):
                    if j == idx:
                        continue
                    if isinstance(other, ast.Constant) and other.value is not None:
                        if project.suppressed(C.RULE_CONFIG_KNOB, path, line):
                            continue
                        findings.append(Finding(
                            path, line, C.RULE_CONFIG_KNOB,
                            f"call to {wname}() passes a literal default "
                            f"for {name!r}, duplicating the registry's "
                            "single default: drop the literal and let "
                            f"{C.KNOB_REGISTRY_FILE} own it",
                        ))

    for path, tree in project.trees.items():
        scan_region(path, tree.body, None)
    for f in project.functions.values():
        if f.node is not None:
            scan_region(f.path, f.node.body, f)

    if global_checks:
        for name in sorted(reg.knobs):
            if name not in reads:
                findings.append(Finding(
                    reg.path, reg.knobs[name], C.RULE_CONFIG_KNOB,
                    f"knob {name} is registered but read nowhere in the "
                    "tree: dead configuration — wire it up or drop it",
                ))
        doc_path = project.root / C.KNOB_DOC_FILE
        try:
            doc_text = doc_path.read_text(encoding="utf-8", errors="replace")
        except OSError:
            doc_text = None
        if doc_text is None:
            findings.append(Finding(
                C.KNOB_DOC_FILE, 0, C.RULE_CONFIG_KNOB,
                "knob documentation file is missing: every registered "
                "knob needs a README anchor",
            ))
        else:
            documented = set(_doc_token_re(reg.prefixes).findall(doc_text))
            for name in sorted(reg.knobs):
                if name not in documented:
                    findings.append(Finding(
                        reg.path, reg.knobs[name], C.RULE_CONFIG_KNOB,
                        f"knob {name} is registered but undocumented in "
                        f"{C.KNOB_DOC_FILE}: regenerate the table with "
                        "`python -m tools.dynacheck --knobs-md`",
                    ))
            for name in sorted(documented):
                if name.startswith(reg.prefixes) and name not in reg.knobs:
                    findings.append(Finding(
                        C.KNOB_DOC_FILE, 0, C.RULE_CONFIG_KNOB,
                        f"{C.KNOB_DOC_FILE} documents {name}, which is "
                        "not a registered knob: doc rot — remove it or "
                        "register it",
                    ))
    findings.sort(key=lambda f: (f.path, f.line, f.message))
    return findings
