"""Engine B: exhaustive bounded-depth interleaving exploration.

A model exposes an initial state, a deterministic set of enabled actions
per state, an invariant check, and a canonical fingerprint. The explorer
runs breadth-first over DISTINCT states (fingerprint-deduplicated), so
every reachable state within the depth bound is visited exactly once and
every invariant is asserted at every one of them — this is exhaustive
state-space exploration, not sampling. Interleaving coverage follows:
two action orders that could disagree necessarily pass through different
states, and both states are visited.

Determinism: actions are explored in the order the model returns them
(models sort by action name), initial states in listed order, and the
frontier is a FIFO — the report is byte-identical across runs.

Violations carry the shortest action trace that reproduces them, so a
model bug report is directly replayable.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Iterable


class Model:
    """Interface Engine B models implement. States are never mutated in
    place by the explorer: ``apply`` must return a NEW state (models
    clone internally — the real allocator/breaker instances they drive
    are cloned field-by-field)."""

    name: str = "model"
    max_depth: int = 10

    def initial_states(self) -> Iterable[tuple[str, Any]]:
        raise NotImplementedError

    def actions(self, state: Any) -> list[tuple[str, Callable[[Any], Any]]]:
        raise NotImplementedError

    def invariants(self, state: Any) -> list[str]:
        raise NotImplementedError

    def fingerprint(self, state: Any) -> Any:
        raise NotImplementedError


@dataclass
class Violation:
    model: str
    trace: tuple[str, ...]
    message: str

    def __str__(self) -> str:
        path = " ; ".join(self.trace) or "<initial>"
        return f"[{self.model}] after [{path}]: {self.message}"


@dataclass
class ModelResult:
    name: str
    states: int = 0
    transitions: int = 0
    depth_reached: int = 0
    exhausted: bool = False   # frontier emptied before the depth bound
    truncated: bool = False   # stopped early: violation cap reached
    violations: list[Violation] = field(default_factory=list)

    @property
    def ok(self) -> bool:
        return not self.violations

    def summary(self) -> str:
        status = "OK" if self.ok else f"{len(self.violations)} VIOLATION(S)"
        if self.truncated:
            frontier = "stopped at the violation cap"
        elif self.exhausted:
            frontier = "state space exhausted"
        else:
            frontier = "depth bound hit"
        return (
            f"model {self.name}: {self.states} states, "
            f"{self.transitions} transitions, depth {self.depth_reached} "
            f"({frontier}) — {status}"
        )


def explore(model: Model, max_violations: int = 8) -> ModelResult:
    res = ModelResult(name=model.name)
    seen: set[Any] = set()
    frontier: list[tuple[Any, tuple[str, ...]]] = []
    for label, state in model.initial_states():
        fp = model.fingerprint(state)
        if fp in seen:
            continue
        seen.add(fp)
        res.states += 1
        for msg in model.invariants(state):
            res.violations.append(Violation(model.name, (label,), msg))
        frontier.append((state, (label,)))
    depth = 0
    while frontier and depth < model.max_depth:
        depth += 1
        nxt: list[tuple[Any, tuple[str, ...]]] = []
        for state, trace in frontier:
            if len(res.violations) >= max_violations:
                break
            for name, apply_fn in model.actions(state):
                new_state = apply_fn(state)
                if new_state is None:
                    continue  # action disabled in this state
                res.transitions += 1
                new_trace = trace + (name,)
                for msg in model.invariants(new_state):
                    res.violations.append(Violation(model.name, new_trace, msg))
                    if len(res.violations) >= max_violations:
                        break
                fp = model.fingerprint(new_state)
                if fp in seen:
                    continue
                seen.add(fp)
                res.states += 1
                nxt.append((new_state, new_trace))
        res.depth_reached = depth
        frontier = nxt
        if len(res.violations) >= max_violations:
            res.truncated = True
            break
    res.exhausted = not frontier and not res.truncated
    return res
