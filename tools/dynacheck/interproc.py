"""Engine A rules: interprocedural dataflow over the project call graph.

Five rules dynalint's single-function pass structurally cannot express,
plus the GUARDED_BY registry drift check:

1. ``transitive-blocking`` — a step-loop hot path (HOT_STEP_FUNCS)
   reaches a device->host sync or event-loop blocker through one or more
   call edges. dynalint flags direct sites; this flags the chain.
2. ``lock-order`` — lock-acquisition-order extraction (lexical nesting +
   call edges + holds-lock pragmas) with deadlock-cycle detection.
3. ``holds-lock-unverified`` — a function annotated
   ``# dynalint: holds-lock(X)`` is called from a context that neither
   holds X lexically nor is itself annotated: the annotation is a claim,
   and this rule makes it a checked one.
4. ``coroutine-leak`` — a call to a project-local ``async def`` whose
   coroutine object is neither awaited, handed to a task spawner,
   returned, nor bound to a name that is used again.
5. ``cursor-discipline`` — a write to ``num_computed_tokens`` /
   pinned-hash / refcount protocol state outside the audited
   commit/rollback/release entry points.
6. ``registry-drift`` — a GUARDED_BY entry whose class/attr no longer
   exists, or whose attribute is mutated nowhere under its declared lock.

Findings suppress with ``# dynacheck: allow-<rule>(<reason>)`` anchored
to the enclosing statement's full line span.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass

from tools.dynacheck import config as C
from tools.dynacheck.callgraph import FuncInfo, LockId, Project


@dataclass(frozen=True)
class Finding:
    path: str
    line: int
    rule: str
    message: str

    def __str__(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"


def run_all(project: Project) -> list[Finding]:
    findings: list[Finding] = []
    for path, line, msg in project.pragma_errors:
        findings.append(Finding(path, line, "malformed-pragma", msg))
    findings.extend(check_transitive_blocking(project))
    findings.extend(check_lock_order(project))
    findings.extend(check_holds_lock(project))
    findings.extend(check_coroutine_leaks(project))
    findings.extend(check_cursor_discipline(project))
    findings.extend(check_registry_drift(project))
    # v2 contract rules live in their own module; imported lazily because
    # contracts.py borrows Finding from here.
    from tools.dynacheck import contracts

    findings.extend(contracts.check_wire_contract(project))
    findings.extend(contracts.check_loop_affinity(project))
    findings.extend(contracts.check_config_knobs(project))
    findings.sort(key=lambda f: (f.path, f.line, f.rule, f.message))
    return findings


# ---------------------------------------------------------------------------
# Rule 1: transitive blocking reachability
# ---------------------------------------------------------------------------


def _hot_roots(project: Project) -> list[FuncInfo]:
    roots: list[FuncInfo] = []
    for suffix, names in C.HOT_STEP_FUNCS.items():
        for info in project.functions.values():
            if info.path.endswith(suffix) and info.name in names:
                roots.append(info)
    roots.sort(key=lambda f: f.key)
    return roots


def check_transitive_blocking(project: Project) -> list[Finding]:
    # One finding per sink site, carrying the shortest chain from the
    # first (sorted) hot root that reaches it — every extra root/chain
    # for the same sink is the same fix.
    best: dict[tuple[str, int], tuple[str, tuple[str, ...]]] = {}
    for root in _hot_roots(project):
        # BFS over call edges; shortest chain per reached function.
        frontier: list[tuple[str, tuple[str, ...]]] = [(root.key, (root.qualname,))]
        visited = {root.key}
        while frontier:
            nxt: list[tuple[str, tuple[str, ...]]] = []
            for key, chain in frontier:
                info = project.functions.get(key)
                if info is None:
                    continue
                if len(chain) > 1:  # depth >= 1: transitive territory
                    for line, what in info.sync_sites:
                        if (info.path, line) in project.sync_ok_lines:
                            continue  # reviewed intentional sync (dynalint)
                        if project.suppressed(
                            C.RULE_TRANSITIVE_BLOCKING, info.path, line
                        ):
                            continue
                        sink = (info.path, line)
                        if sink not in best or len(chain) < len(best[sink][1]):
                            best[sink] = (what, chain)
                for cs in info.calls:
                    for t in sorted(cs.targets):
                        if t in visited:
                            continue
                        tinfo = project.functions.get(t)
                        if tinfo is None:
                            continue
                        # The registered sync primitives are sinks, not
                        # waypoints: CALLING fetch_replicated is the
                        # blocking event (recorded at the call site);
                        # its implementation is not separate news.
                        if tinfo.name in C.HOST_SYNC_FNS:
                            continue
                        visited.add(t)
                        nxt.append((t, chain + (tinfo.qualname,)))
            frontier = nxt
    out: list[Finding] = []
    for (path, line), (what, chain) in sorted(best.items()):
        out.append(Finding(
            path, line, C.RULE_TRANSITIVE_BLOCKING,
            f"{what} is reachable from step-loop hot path "
            f"{chain[0]!r} via {' -> '.join(chain)}: "
            "a blocking sync here serializes planning with "
            "device compute; move the landing to the commit "
            "side or pragma the sink with "
            "`# dynacheck: allow-transitive-blocking(...)`",
        ))
    return out


# ---------------------------------------------------------------------------
# Rule 2: lock-order extraction + deadlock cycles
# ---------------------------------------------------------------------------


def _lock_str(lid: LockId) -> str:
    return f"{lid[0]}.{lid[1]}"


def _locks_inside(project: Project) -> dict[str, set[LockId]]:
    """Fixpoint: locks acquired in each function or any transitive callee."""
    inside: dict[str, set[LockId]] = {
        k: {a.lock for a in f.lock_acquires}
        for k, f in project.functions.items()
    }
    changed = True
    rounds = 0
    while changed and rounds < 50:
        changed = False
        rounds += 1
        for k, f in project.functions.items():
            cur = inside[k]
            before = len(cur)
            for cs in f.calls:
                for t in cs.targets:
                    cur |= inside.get(t, set())
            if len(cur) != before:
                changed = True
    return inside


def _resolve_pragma_lock(project: Project, name: str) -> LockId | None:
    owners = sorted({lid for lid in project.locks if lid[1] == name})
    if len({o[0] for o in owners}) == 1:
        return owners[0]
    return None


def check_lock_order(project: Project) -> list[Finding]:
    inside = _locks_inside(project)
    # edge (src, dst) -> list of witnesses (path, line, description)
    edges: dict[tuple[LockId, LockId], list[tuple[str, int, str]]] = {}

    def add_edge(src: LockId, dst: LockId, path: str, line: int, how: str) -> None:
        if project.suppressed(C.RULE_LOCK_ORDER, path, line):
            return
        edges.setdefault((src, dst), []).append((path, line, how))

    for f in project.functions.values():
        pragma_locks = [
            lid for lid in (
                _resolve_pragma_lock(project, nm) for nm in sorted(f.holds_pragmas)
            ) if lid is not None
        ]
        # Lexical nesting (+ pragma-held context). Two locks of the SAME
        # identity in one with-statement (two instances of one class)
        # produce a self-edge here — a deadlock unless callers impose a
        # global acquisition order.
        for acq in f.lock_acquires:
            for h in acq.held_before:
                add_edge(h, acq.lock, f.path, acq.line, "nested with")
            if not acq.held_before:
                for p in pragma_locks:
                    add_edge(p, acq.lock, f.path, acq.line, "held via holds-lock pragma")
        # Call edges: held here -> acquired inside the callee.
        for cs in f.calls:
            held = list(cs.held_locks)
            if not held and pragma_locks:
                held = pragma_locks
            if not held:
                continue
            for t in cs.targets:
                for m in inside.get(t, ()):
                    for h in held:
                        add_edge(
                            h, m, f.path, cs.line,
                            f"call into {project.functions[t].qualname} "
                            f"which acquires {_lock_str(m)}",
                        )

    # Cycle detection over the lock-order digraph (self-loops included).
    graph: dict[LockId, set[LockId]] = {}
    for (src, dst) in edges:
        graph.setdefault(src, set()).add(dst)
        graph.setdefault(dst, set())
    cycles = _find_cycles(graph)

    out: list[Finding] = []
    for cyc in cycles:
        members = set(cyc)
        # Witness with the ACTUAL edges inside the cycle's node set — the
        # sorted SCC listing is a set, not an edge sequence, so consecutive
        # sorted pairs need not be edges at all.
        cyc_edges = sorted(
            (src, dst) for (src, dst) in edges
            if src in members and dst in members
        )
        witnesses = [w for p in cyc_edges for w in edges[p]]
        if not witnesses:
            continue  # every edge in this SCC was pragma-suppressed
        wit_path, wit_line, _ = min(witnesses)
        detail = "; ".join(
            f"{_lock_str(a)}->{_lock_str(b)} at "
            + ", ".join(f"{p}:{ln} ({how})" for p, ln, how in sorted(edges[(a, b)])[:3])
            for a, b in cyc_edges
        )
        if len(cyc) == 1:
            msg = (
                f"lock {_lock_str(cyc[0])} is acquired while an instance of "
                f"itself is already held ({detail}): two instances of this "
                "lock taken concurrently in opposite orders deadlock; impose "
                "a global acquisition order and pragma the site with "
                "`# dynacheck: allow-lock-order(...)`"
            )
        else:
            names = " , ".join(_lock_str(l) for l in cyc)
            msg = (
                f"inconsistent lock acquisition order: locks {{{names}}} "
                f"form a cycle ({detail}); threads taking these locks in "
                "different orders can deadlock"
            )
        out.append(Finding(wit_path, wit_line, C.RULE_LOCK_ORDER, msg))
    return out


def _find_cycles(graph: dict[LockId, set[LockId]]) -> list[tuple[LockId, ...]]:
    """Elementary cycles, deterministically: self-loops plus one cycle per
    strongly connected component of size > 1 (reported as the sorted SCC —
    a full Johnson enumeration would drown the report in rotations)."""
    cycles: list[tuple[LockId, ...]] = []
    for n in sorted(graph):
        if n in graph.get(n, ()):
            cycles.append((n,))
    for scc in _sccs(graph):
        if len(scc) > 1:
            cycles.append(tuple(sorted(scc)))
    return sorted(cycles)


def _sccs(graph: dict[LockId, set[LockId]]) -> list[list[LockId]]:
    """Tarjan, iterative, deterministic node order."""
    index: dict[LockId, int] = {}
    low: dict[LockId, int] = {}
    on_stack: set[LockId] = set()
    stack: list[LockId] = []
    sccs: list[list[LockId]] = []
    counter = [0]

    for root in sorted(graph):
        if root in index:
            continue
        work: list[tuple[LockId, list[LockId], int]] = [
            (root, sorted(graph.get(root, ())), 0)
        ]
        while work:
            node, succs, i = work.pop()
            if i == 0:
                index[node] = low[node] = counter[0]
                counter[0] += 1
                stack.append(node)
                on_stack.add(node)
            recurse = False
            while i < len(succs):
                s = succs[i]
                i += 1
                if s not in index:
                    work.append((node, succs, i))
                    work.append((s, sorted(graph.get(s, ())), 0))
                    recurse = True
                    break
                if s in on_stack:
                    low[node] = min(low[node], index[s])
            if recurse:
                continue
            if low[node] == index[node]:
                comp: list[LockId] = []
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    comp.append(w)
                    if w == node:
                        break
                sccs.append(comp)
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])
    return sccs


# ---------------------------------------------------------------------------
# Rule 3: holds-lock pragma verification
# ---------------------------------------------------------------------------


def check_holds_lock(project: Project) -> list[Finding]:
    out: list[Finding] = []
    for key in sorted(project.functions):
        f = project.functions[key]
        if not f.holds_pragmas:
            continue
        for lock_name in sorted(f.holds_pragmas):
            for caller_key, cs in sorted(
                project.callers.get(key, []), key=lambda kc: (kc[0], kc[1].line)
            ):
                caller = project.functions.get(caller_key)
                if caller is None:
                    continue
                if any(h[1] == lock_name for h in cs.held_locks):
                    continue  # lexically held at the call
                if lock_name in caller.holds_pragmas:
                    continue  # caller carries (and is checked for) the claim
                if caller.name == "__init__":
                    continue  # construction precedes sharing
                if project.suppressed(
                    C.RULE_HOLDS_LOCK_UNVERIFIED, caller.path, cs.line
                ):
                    continue
                out.append(Finding(
                    caller.path, cs.line, C.RULE_HOLDS_LOCK_UNVERIFIED,
                    f"{caller.qualname} calls {f.qualname} (annotated "
                    f"holds-lock({lock_name})) without holding {lock_name}: "
                    "acquire the lock, annotate the caller with "
                    f"`# dynalint: holds-lock({lock_name})`, or pragma with "
                    "`# dynacheck: allow-holds-lock-unverified(...)`",
                ))
    return out


# ---------------------------------------------------------------------------
# Rule 4: coroutine-leak dataflow
# ---------------------------------------------------------------------------

_OK_USAGE = {"await", "sink", "return", "yield"}


def check_coroutine_leaks(project: Project) -> list[Finding]:
    out: list[Finding] = []
    for key in sorted(project.functions):
        f = project.functions[key]
        for cs in f.calls:
            async_targets = [
                t for t in cs.targets
                if project.functions[t].is_async
                and not project.functions[t].is_generator
            ]
            if not async_targets or cs.usage in _OK_USAGE:
                continue
            if cs.usage == "other":
                continue  # handed onward / stored: ownership moved
            if project.suppressed(C.RULE_CORO_LEAK, f.path, cs.line):
                continue
            tname = project.functions[async_targets[0]].qualname
            if cs.usage == "dropped":
                out.append(Finding(
                    f.path, cs.line, C.RULE_CORO_LEAK,
                    f"coroutine {tname}() is created and immediately "
                    "dropped: the body never runs (Python logs 'never "
                    "awaited' at gc time at best); await it, or hand it "
                    "to a task spawner",
                ))
            elif cs.usage.startswith("bound:"):
                name = cs.usage.split(":", 1)[1]
                if _name_reused_after(f, name, cs.line):
                    continue
                out.append(Finding(
                    f.path, cs.line, C.RULE_CORO_LEAK,
                    f"coroutine {tname}() is bound to {name!r} but the "
                    "name is never used again in this scope: the "
                    "coroutine escapes unawaited and unspawned",
                ))
    return out


def _name_reused_after(f: FuncInfo, name: str, line: int) -> bool:
    if f.node is None:
        return True  # no body available: stay quiet
    for sub in ast.walk(f.node):
        if (
            isinstance(sub, ast.Name)
            and sub.id == name
            and isinstance(sub.ctx, ast.Load)
            and sub.lineno >= line
        ):
            return True
    return False


# ---------------------------------------------------------------------------
# Rule 5: cursor discipline
# ---------------------------------------------------------------------------


def check_cursor_discipline(project: Project) -> list[Finding]:
    out: list[Finding] = []
    for key in sorted(project.functions):
        f = project.functions[key]
        audited: set[str] = set()
        for suffix, quals in C.AUDITED_CURSOR_WRITERS.items():
            if f.path.endswith(suffix):
                audited = quals
                break
        if f.qualname in audited:
            continue
        for w in f.writes:
            if w.attr not in C.CURSOR_ATTRS:
                continue
            if w.receiver in ("<local>", "<global>"):
                continue  # bare-name stores are not protocol-state writes
            if project.suppressed(C.RULE_CURSOR, f.path, w.line):
                continue
            out.append(Finding(
                f.path, w.line, C.RULE_CURSOR,
                f"write to {w.receiver}.{w.attr} ({C.CURSOR_ATTRS[w.attr]}) "
                f"in {f.qualname}, which is not an audited "
                "commit/rollback/release entry point: route the mutation "
                "through the audited writers (tools/dynacheck/config.py "
                "AUDITED_CURSOR_WRITERS) or pragma with "
                "`# dynacheck: allow-cursor-discipline(...)`",
            ))
    return out


# ---------------------------------------------------------------------------
# Rule 6: GUARDED_BY registry drift
# ---------------------------------------------------------------------------


def check_registry_drift(project: Project) -> list[Finding]:
    out: list[Finding] = []
    paths = sorted({f.path for f in project.functions.values()})
    # A registered-but-absent file is drift only on a tree scan — a
    # narrow scan (one fixture file, one module) proves nothing about
    # the registry's other entries.
    tree_scan = any(p.startswith("dynamo_tpu/") for p in paths)
    for suffix in sorted(C.GUARDED_BY):
        matches = [p for p in paths if p.endswith(suffix)]
        if not matches:
            if not suffix.startswith("dynamo_tpu/") or not tree_scan:
                continue
            out.append(Finding(
                suffix, 0, C.RULE_REGISTRY_DRIFT,
                f"GUARDED_BY registers {suffix} but no scanned file "
                "matches it: the module moved or was deleted — update "
                "tools/dynalint/config.py",
            ))
            continue
        path = matches[0]
        file_funcs = [f for f in project.functions.values() if f.path == path]
        for (scope, attr), lock in sorted(
            C.GUARDED_BY[suffix].items(), key=lambda kv: (str(kv[0][0]), kv[0][1])
        ):
            if scope is not None and path not in project.classes.get(scope, set()):
                out.append(Finding(
                    path, 0, C.RULE_REGISTRY_DRIFT,
                    f"GUARDED_BY entry ({scope}, {attr}): class {scope} "
                    f"no longer exists in {path}",
                ))
                continue
            writes = _registry_writes(file_funcs, scope, attr)
            if not writes:
                out.append(Finding(
                    path, 0, C.RULE_REGISTRY_DRIFT,
                    f"GUARDED_BY entry ({scope}, {attr}) guarded by {lock}: "
                    "attribute is mutated nowhere in the file — stale "
                    "entry, tighten the registry",
                ))
                continue
            if lock == C.EXTERNAL:
                continue
            lock_exists = any(
                lid[1] == lock and (scope is None or lid[0] == scope)
                for lid in project.locks
            )
            if not lock_exists:
                out.append(Finding(
                    path, 0, C.RULE_REGISTRY_DRIFT,
                    f"GUARDED_BY entry ({scope}, {attr}): declared lock "
                    f"{lock} is not constructed anywhere in scope "
                    f"{scope or path}",
                ))
                continue
            guarded_writes = [
                (f, w) for f, w in writes
                if any(h[1] == lock for h in w.held)
                or lock in f.holds_pragmas
            ]
            nontrivial = [
                (f, w) for f, w in writes
                if f.name != "__init__" and f.qualname != "<module>"
            ]
            if nontrivial and not guarded_writes:
                first = min(w.line for _, w in nontrivial)
                out.append(Finding(
                    path, first, C.RULE_REGISTRY_DRIFT,
                    f"GUARDED_BY entry ({scope}, {attr}) declares lock "
                    f"{lock}, but no mutation site holds it (lexically or "
                    "via holds-lock pragma): the attribute migrated to a "
                    "different lock or the discipline is broken — fix the "
                    "registry or the code",
                ))
    return out


def _registry_writes(file_funcs, scope, attr):
    out = []
    for f in file_funcs:
        in_scope = (
            scope is None
            or f.qualname.startswith(f"{scope}.")
        )
        if not in_scope:
            continue
        for w in f.writes:
            if w.attr != attr:
                continue
            if scope is None:
                if w.receiver != "<global>":
                    continue
            else:
                if w.receiver not in ("self", "self(alias)"):
                    continue
            out.append((f, w))
    return out
