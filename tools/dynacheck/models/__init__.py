"""Engine B models: executable miniatures of the three hairiest state
machines, explored exhaustively by :mod:`tools.dynacheck.explore`.

- ``allocator`` drives the REAL :class:`DeviceBlockAllocator` (pure
  Python) through admit/alloc/commit/release/evict/clear interleavings
  over a shared-prefix two-sequence world.
- ``cursor`` models the async-exec + megastep plan/dispatch/commit
  cursor protocol against a synchronous reference trace.
- ``pp-wavefront`` models the pipeline-parallel megastep's cross-group
  commit ordering (drain-before-next-entry) against per-group
  synchronous traces.
- ``breaker`` drives the REAL :class:`CircuitBreaker` under a virtual
  clock, including the cancelled-probe re-arm.
- ``quarantine`` models EndpointClient's lease-expiry quarantine machine
  (grace windows, due sweeps, reconcile) against ground-truth liveness.
- ``keepalive`` models the store client's lease keepalive + session
  resurrection protocol (same-id re-grant, task cancellation, re-puts).
- ``planner`` drives the REAL :class:`PlannerController` on a virtual
  timeline through demand swings, SLO misses and control-plane outages.
"""

from __future__ import annotations

from tools.dynacheck.models.allocator import AllocatorModel
from tools.dynacheck.models.breaker import BreakerModel
from tools.dynacheck.models.cursor import CursorModel, PPWavefrontModel
from tools.dynacheck.models.keepalive import KeepaliveModel
from tools.dynacheck.models.planner import PlannerModel
from tools.dynacheck.models.quarantine import QuarantineModel

ALL_MODELS = (
    AllocatorModel, CursorModel, PPWavefrontModel, BreakerModel,
    QuarantineModel, KeepaliveModel, PlannerModel,
)
