"""Engine B models: executable miniatures of the three hairiest state
machines, explored exhaustively by :mod:`tools.dynacheck.explore`.

- ``allocator`` drives the REAL :class:`DeviceBlockAllocator` (pure
  Python) through admit/alloc/commit/release/evict/clear interleavings
  over a shared-prefix two-sequence world.
- ``cursor`` models the async-exec + megastep plan/dispatch/commit
  cursor protocol against a synchronous reference trace.
- ``breaker`` drives the REAL :class:`CircuitBreaker` under a virtual
  clock, including the cancelled-probe re-arm.
"""

from __future__ import annotations

from tools.dynacheck.models.allocator import AllocatorModel
from tools.dynacheck.models.breaker import BreakerModel
from tools.dynacheck.models.cursor import CursorModel

ALL_MODELS = (AllocatorModel, CursorModel, BreakerModel)
