"""Allocator model: the REAL DeviceBlockAllocator under every admit /
alloc / commit / abort / release / evict / clear interleaving.

World: 3 physical blocks, two sequences whose 2-block hash chains share
their first block (A: [101, 102], B: [101, 202]) — the shared prefix is
what makes refcount conservation interesting (dedup on commit, shared
pins, LRU revival). One initial-state variant arms the ``on_evict``
demotion hook (the host-KV-tier shape, where eviction does NOT emit
``removed``), the other leaves eviction emitting.

Invariants checked at EVERY reachable state:

- **block conservation** — free + committed + outstanding partials is
  exactly the capacity, with no block id in two places at once;
- **refcount conservation** — each committed block's refcount equals the
  number of model-side pins on its hash (no double-release can ever make
  this balance);
- **LRU consistency** — inactive is exactly the refcount-0 slice of the
  committed set;
- **event balance** — ``on_stored``/``on_removed`` callbacks (the
  router's view of this worker) track the committed set exactly: no
  double-remove, no remove-before-store, no pinned-hash leak;
- **drain leak-freedom** — in any quiescent state (nothing pinned, no
  partials, cache cleared) every block is back on the free list.
"""

from __future__ import annotations

from collections import OrderedDict, deque
from typing import Any, Callable

from dynamo_tpu.engine.block_allocator import DeviceBlockAllocator, OutOfBlocksError, _Committed
from tools.dynacheck import config as C
from tools.dynacheck.explore import Model

CAPACITY = 3
CHAINS = {"A": (101, 102), "B": (101, 202)}


class _State:
    def __init__(self, demote: bool):
        self.demote = demote
        self.events: list[tuple[str, int]] = []     # ("stored"|"removed"|"demoted", hash)
        self.alloc = DeviceBlockAllocator(
            CAPACITY, block_size=4, enable_prefix_caching=True,
            on_stored=self._on_stored, on_removed=self._on_removed,
        )
        if demote:
            self.alloc.on_evict = self._on_evict
        # Per-sequence protocol mirror: pinned hash list (what
        # _release_blocks would release), outstanding partial block id,
        # next chain index to fill.
        self.pinned: dict[str, list[int]] = {"A": [], "B": []}
        self.partial: dict[str, int | None] = {"A": None, "B": None}
        self.next_idx: dict[str, int] = {"A": 0, "B": 0}
        self.started: dict[str, bool] = {"A": False, "B": False}

    # -- event hooks (the router's view) -----------------------------------

    def _on_stored(self, hashes: list[int], parent: int | None) -> None:
        for h in hashes:
            self.events.append(("stored", h))

    def _on_removed(self, hashes: list[int]) -> None:
        for h in hashes:
            self.events.append(("removed", h))

    def _on_evict(self, block_id: int, h: int, parent: int | None) -> None:
        self.events.append(("demoted", h))

    # -- cloning (the explorer never mutates in place) ---------------------

    def clone(self) -> "_State":
        new = _State.__new__(_State)
        new.demote = self.demote
        new.events = list(self.events)
        a, src = DeviceBlockAllocator.__new__(DeviceBlockAllocator), self.alloc
        a.capacity = src.capacity
        a.block_size = src.block_size
        a.enable_prefix_caching = src.enable_prefix_caching
        a._free = deque(src._free)
        a._by_hash = {
            h: _Committed(b.block_id, b.block_hash, b.parent_hash, b.refcount)
            for h, b in src._by_hash.items()
        }
        # _inactive must reference the SAME _Committed objects as _by_hash.
        a._inactive = OrderedDict((h, a._by_hash[h]) for h in src._inactive)
        a._partials = src._partials
        a.prefix_queries = src.prefix_queries
        a.prefix_hits = src.prefix_hits
        a.on_stored = new._on_stored
        a.on_removed = new._on_removed
        a.on_evict = new._on_evict if self.demote else None
        new.alloc = a
        new.pinned = {k: list(v) for k, v in self.pinned.items()}
        new.partial = dict(self.partial)
        new.next_idx = dict(self.next_idx)
        new.started = dict(self.started)
        return new


class AllocatorModel(Model):
    name = "allocator"
    max_depth = C.MODEL_DEPTHS["allocator"]

    def initial_states(self):
        yield "init", _State(demote=False)
        yield "init-demote-hook", _State(demote=True)

    # -- actions -----------------------------------------------------------

    def actions(self, state: _State) -> list[tuple[str, Callable[[Any], Any]]]:
        acts: list[tuple[str, Callable[[Any], Any]]] = []
        for s in ("A", "B"):
            if not state.started[s]:
                acts.append((f"admit_{s}", self._mk(self._admit, s)))
            else:
                chain = CHAINS[s]
                if state.partial[s] is None and state.next_idx[s] < len(chain):
                    acts.append((f"alloc_{s}", self._mk(self._alloc, s)))
                if state.partial[s] is not None:
                    acts.append((f"commit_{s}", self._mk(self._commit, s)))
                    acts.append((f"abort_{s}", self._mk(self._abort, s)))
                acts.append((f"release_{s}", self._mk(self._release, s)))
        # Peer KV import (the disagg/kv_transfer path): content arrives
        # from another worker and registers as cached-but-unpinned.
        acts.append(("import_peer", self._import_peer))
        acts.append(("clear_cache", self._clear))
        acts.sort(key=lambda kv: kv[0])
        return acts

    @staticmethod
    def _mk(fn, s):
        return lambda state: fn(state, s)

    @staticmethod
    def _admit(state: _State, s: str) -> _State:
        st = state.clone()
        ids = st.alloc.acquire_cached(list(CHAINS[s]))
        st.pinned[s] = list(CHAINS[s][: len(ids)])
        st.next_idx[s] = len(ids)
        st.started[s] = True
        return st

    @staticmethod
    def _alloc(state: _State, s: str) -> _State | None:
        st = state.clone()
        try:
            st.partial[s] = st.alloc.alloc()
        except OutOfBlocksError:
            return None  # legitimate refusal: nothing changed
        return st

    @staticmethod
    def _commit(state: _State, s: str) -> _State:
        st = state.clone()
        chain = CHAINS[s]
        idx = st.next_idx[s]
        parent = chain[idx - 1] if idx > 0 else None
        st.alloc.commit(st.partial[s], chain[idx], parent)
        st.partial[s] = None
        st.pinned[s].append(chain[idx])
        st.next_idx[s] = idx + 1
        return st

    @staticmethod
    def _abort(state: _State, s: str) -> _State:
        st = state.clone()
        st.alloc.free_partial(st.partial[s])
        st.partial[s] = None
        return st

    @staticmethod
    def _release(state: _State, s: str) -> _State:
        # Mirrors EngineCore._release_blocks: partials back to the free
        # list, pins released, then the slate is clean for re-admission.
        st = state.clone()
        if st.partial[s] is not None:
            st.alloc.free_partial(st.partial[s])
            st.partial[s] = None
        st.alloc.release(st.pinned[s])
        st.pinned[s] = []
        st.next_idx[s] = 0
        st.started[s] = False
        return st

    @staticmethod
    def _import_peer(state: _State) -> _State | None:
        # Mirrors import_blocks: alloc_for_import + register_inactive,
        # dedup against already-cached content (the canonical id wins and
        # the fresh block goes straight back to the free list).
        st = state.clone()
        h, parent = CHAINS["B"][1], CHAINS["B"][0]
        try:
            bid = st.alloc.alloc_for_import()
        except OutOfBlocksError:
            return None
        st.alloc.register_inactive(bid, h, parent)
        return st

    @staticmethod
    def _clear(state: _State) -> _State:
        st = state.clone()
        st.alloc.clear_cache()
        return st

    # -- invariants --------------------------------------------------------

    def invariants(self, state: _State) -> list[str]:
        out: list[str] = []
        a = state.alloc
        free = list(a._free)
        committed_ids = [b.block_id for b in a._by_hash.values()]
        partials = [b for b in state.partial.values() if b is not None]
        everywhere = free + committed_ids + partials
        if sorted(everywhere) != list(range(CAPACITY)):
            out.append(
                "block conservation broken: free=%s committed=%s partials=%s "
                "(capacity %d)" % (free, committed_ids, partials, CAPACITY)
            )
        if a._partials != len(partials):
            out.append(
                f"partial count drift: allocator says {a._partials}, "
                f"model holds {len(partials)}"
            )
        # Refcount conservation against model pins.
        pins: dict[int, int] = {}
        for s in ("A", "B"):
            for h in state.pinned[s]:
                pins[h] = pins.get(h, 0) + 1
        for h, blk in a._by_hash.items():
            if blk.refcount < 0:
                out.append(f"negative refcount on hash {h}: {blk.refcount}")
            if blk.refcount != pins.get(h, 0):
                out.append(
                    f"refcount conservation broken for hash {h}: allocator "
                    f"says {blk.refcount}, model pins {pins.get(h, 0)} "
                    "(double-release or leaked pin)"
                )
        # Inactive LRU is exactly the refcount-0 slice.
        for h in a._inactive:
            if h not in a._by_hash:
                out.append(f"inactive hash {h} missing from _by_hash")
            elif a._inactive[h] is not a._by_hash[h]:
                out.append(f"inactive and _by_hash disagree on hash {h} identity")
            elif a._by_hash[h].refcount != 0:
                out.append(f"pinned hash {h} sits in the inactive LRU")
        for h, blk in a._by_hash.items():
            if blk.refcount == 0 and h not in a._inactive:
                out.append(f"refcount-0 hash {h} not reclaimable (LRU leak)")
        # Event balance: the router's stored-set must equal the committed set.
        live: set[int] = set()
        for kind, h in state.events:
            if kind == "stored":
                if h in live:
                    out.append(f"hash {h} stored twice without removal")
                live.add(h)
            else:  # removed / demoted both end router-visible residency
                if h not in live:
                    out.append(f"hash {h} {kind} but never stored")
                live.discard(h)
        if live != set(a._by_hash):
            out.append(
                f"router residency drift: events say {sorted(live)}, "
                f"allocator holds {sorted(a._by_hash)} (pinned-hash leak)"
            )
        # Drain leak-freedom: quiescent + empty cache -> everything free.
        if not a._by_hash and not partials and len(free) != CAPACITY:
            out.append(f"leak at quiescence: only {len(free)}/{CAPACITY} blocks free")
        return out

    def fingerprint(self, state: _State) -> Any:
        a = state.alloc
        return (
            state.demote,
            tuple(a._free),
            tuple(sorted(
                (h, b.block_id, b.parent_hash, b.refcount)
                for h, b in a._by_hash.items()
            )),
            tuple(a._inactive),
            a._partials,
            tuple(
                (s, tuple(state.pinned[s]), state.partial[s],
                 state.next_idx[s], state.started[s])
                for s in ("A", "B")
            ),
            # Router residency (not the raw event list — unbounded).
            tuple(sorted(_live_hashes(state.events))),
        )


def _live_hashes(events: list[tuple[str, int]]) -> set[int]:
    live: set[int] = set()
    for kind, h in events:
        if kind == "stored":
            live.add(h)
        else:
            live.discard(h)
    return live
