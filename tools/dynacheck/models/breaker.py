"""Breaker model: the REAL CircuitBreaker (runtime/dataplane.py) under a
virtual clock, explored through every interleaving of failures,
successes, dials, probe outcomes — including the cancelled probe that
never reports back — and time advances.

Invariants checked at EVERY reachable state:

- **legal states** — the breaker is always exactly one of
  closed/open/half-open with sane counters;
- **fail-fast while open** — inside the reset window an open breaker
  rejects every dial (no traffic leaks to a known-bad address);
- **single probe** — at most one half-open probe is admitted per reset
  window (a thundering herd of probes would defeat the breaker);
- **no wedge (liveness)** — from ANY reachable state, advancing the
  clock lets a dial through within two reset windows: a cancelled
  probe (dial admitted, outcome never reported) must re-arm rather
  than parking the address forever — the exact bug shape the
  stale-probe re-arm exists for;
- **recovery** — a probe that succeeds closes the breaker immediately.
"""

from __future__ import annotations

from typing import Any, Callable

from dynamo_tpu.runtime.dataplane import CircuitBreaker
from tools.dynacheck import config as C
from tools.dynacheck.explore import Model

THRESHOLD = 2
RESET_S = 2.0
HALF = RESET_S / 2


class _State:
    def __init__(self, breaker_cls: type = CircuitBreaker):
        self.now = 0.0
        self.breaker = breaker_cls(
            threshold=THRESHOLD, reset_s=RESET_S, clock=self._clock
        )
        # Dials admitted while not closed whose outcome is still pending
        # (a cancelled probe simply never reports).
        self.probes_pending = 0

    def _clock(self) -> float:
        return self.now

    def clone(self) -> "_State":
        new = _State.__new__(_State)
        new.now = self.now
        src = self.breaker
        b = type(src)(threshold=THRESHOLD, reset_s=RESET_S, clock=new._clock)
        b.state = src.state
        b.consecutive_failures = src.consecutive_failures
        b.opens_total = src.opens_total
        b._opened_at = src._opened_at
        b._probe_at = src._probe_at
        new.breaker = b
        new.probes_pending = self.probes_pending
        return new


class BreakerModel(Model):
    name = "breaker"
    max_depth = C.MODEL_DEPTHS["breaker"]
    # Injection point for the fixture suite: a deliberately broken
    # breaker class proves the invariants can fire.
    breaker_cls: type = CircuitBreaker

    def initial_states(self):
        yield "init", _State(self.breaker_cls)

    def actions(self, state: _State) -> list[tuple[str, Callable[[Any], Any]]]:
        acts: list[tuple[str, Callable[[Any], Any]]] = [
            ("advance_full", lambda s: self._advance(s, RESET_S)),
            ("advance_half", lambda s: self._advance(s, HALF)),
            ("dial", self._dial),
            ("fail", self._fail),
            ("success", self._success),
        ]
        if state.probes_pending > 0:
            acts.append(("probe_cancelled", self._probe_cancelled))
            acts.append(("probe_fail", self._probe_fail))
            acts.append(("probe_success", self._probe_success))
        acts.sort(key=lambda kv: kv[0])
        return acts

    @staticmethod
    def _advance(state: _State, dt: float) -> _State:
        st = state.clone()
        st.now += dt
        return st

    @staticmethod
    def _dial(state: _State) -> _State:
        st = state.clone()
        was_closed = st.breaker.state == CircuitBreaker.CLOSED
        admitted = st.breaker.allow()
        if admitted and not was_closed:
            st.probes_pending += 1
        return st

    @staticmethod
    def _fail(state: _State) -> _State:
        # A non-probe failure (e.g. an established conn dying).
        st = state.clone()
        st.breaker.record_failure()
        return st

    @staticmethod
    def _success(state: _State) -> _State:
        st = state.clone()
        st.breaker.record_success()
        return st

    @staticmethod
    def _probe_cancelled(state: _State) -> _State:
        # The probe task was cancelled mid-dial: no outcome is EVER
        # reported. The stale-probe re-arm must absorb this.
        st = state.clone()
        st.probes_pending -= 1
        return st

    @staticmethod
    def _probe_fail(state: _State) -> _State:
        st = state.clone()
        st.probes_pending -= 1
        st.breaker.record_failure()
        return st

    @staticmethod
    def _probe_success(state: _State) -> _State:
        st = state.clone()
        st.probes_pending -= 1
        st.breaker.record_success()
        return st

    # -- invariants --------------------------------------------------------

    def invariants(self, state: _State) -> list[str]:
        out: list[str] = []
        b = state.breaker
        if b.state not in (
            CircuitBreaker.CLOSED, CircuitBreaker.OPEN, CircuitBreaker.HALF_OPEN
        ):
            out.append(f"illegal breaker state {b.state!r}")
        if b.consecutive_failures < 0 or b.opens_total < 0:
            out.append(
                f"negative counters: failures={b.consecutive_failures}, "
                f"opens={b.opens_total}"
            )
        if (
            b.state == CircuitBreaker.CLOSED
            and b.consecutive_failures >= THRESHOLD
        ):
            out.append(
                f"closed with {b.consecutive_failures} consecutive failures "
                f"(threshold {THRESHOLD}): the breaker failed to open"
            )
        # Fail-fast while open: inside the reset window a dial must be
        # rejected (checked on a clone — allow() mutates).
        if b.state == CircuitBreaker.OPEN and state.now - b._opened_at < RESET_S:
            probe = state.clone()
            if probe.breaker.allow():
                out.append(
                    "open breaker admitted a dial inside the reset window "
                    f"(opened_at={b._opened_at}, now={state.now})"
                )
        # Single probe per window: half-open with a fresh probe must hold
        # further dials.
        if (
            b.state == CircuitBreaker.HALF_OPEN
            and state.now - b._probe_at < RESET_S
            and state.probes_pending > 0
        ):
            probe = state.clone()
            if probe.breaker.allow():
                out.append(
                    "half-open breaker admitted a second concurrent probe "
                    f"(probe_at={b._probe_at}, now={state.now}, "
                    f"pending={state.probes_pending})"
                )
        # No wedge (liveness): advancing the clock must let a dial
        # through within two reset windows, from ANY state — a cancelled
        # probe must never park the address forever.
        sim = state.clone()
        admitted = False
        for _ in range(2):
            sim.now += RESET_S
            if sim.breaker.allow():
                admitted = True
                break
        if not admitted:
            out.append(
                f"breaker wedged: state={b.state}, no dial admitted within "
                f"2 reset windows of clock advance (probes_pending="
                f"{state.probes_pending})"
            )
        else:
            # Recovery: the admitted dial's success must close it.
            sim.breaker.record_success()
            if sim.breaker.state != CircuitBreaker.CLOSED:
                out.append(
                    "probe success did not close the breaker "
                    f"(state={sim.breaker.state})"
                )
        return out

    def fingerprint(self, state: _State) -> Any:
        b = state.breaker
        # Time is canonicalized as bounded deltas (all advances are
        # multiples of reset_s/2, so these are discrete); beyond two
        # windows the behavior is time-invariant.
        cap = RESET_S * 2
        d_open = min(cap, state.now - b._opened_at)
        d_probe = min(cap, state.now - b._probe_at)
        return (
            b.state,
            min(b.consecutive_failures, THRESHOLD + 2),
            d_open, d_probe,
            min(state.probes_pending, 3),
        )
