"""Cursor model: the async-exec + megastep plan/dispatch/commit protocol
against a synchronous reference trace.

The real machinery (engine/core.py) plans step N+1 against optimistic
cursor overlays while step N is in flight, fuses k decode iterations into
one dispatch, and rolls EVERY late outcome — device-watched EOS inside a
megastep, host-only stops the device cannot see, rejected speculative
drafts — back through the ``num_computed_tokens`` cursor. This model
reproduces exactly that algebra with a deterministic token oracle, and
the explorer drives it through every interleaving of:

- ``step_sync``      plan + commit in place (the async_exec=off loop),
- ``step_async_k*``  plan k=1/k=2 against the overlay, then commit the
                     previous in-flight step (the one-step-ahead loop),
- ``step_verify``    a speculative verify step whose advance is
                     data-dependent (non-deterministic: the next plan is
                     barred until it commits, like the engine's barrier),
- ``step_fused_verify`` the UNIVERSAL megastep (ISSUE 12): the verify
                     row resolves accept/reject ON DEVICE inside a fused
                     dispatch — a rejected draft's K/V write sits past
                     the cursor and is overwritten in place — and the
                     lane keeps decoding for the remaining scanned
                     iteration, emitting (accepted + 1) + 1 tokens in
                     one commit (still non-deterministic: the advance is
                     data-dependent),
- ``step_device_draft`` ON-DEVICE n-gram drafting (ISSUE 18): the lane
                     drafts from a device-resident history ring BETWEEN
                     the megastep's inner iterations, so every round is
                     draft -> verify -> accept without leaving the
                     dispatch. A hit round lands accepted + 1 tokens, a
                     miss round degenerates to the plain scanned decode
                     token; a host-only stop inside the emission must
                     truncate at commit AND the next plan must draft
                     from the post-commit truth (the host-side ring
                     repack IS the rollback),
- ``drain``          commit the in-flight step with no new plan,
- ``cancel``         client cancel mid-flight (zombie-lane discard).

Initial-state variants place a device-watched EOS and a host-only stop at
different stream positions, plus a draft-acceptance pattern for verify —
including drafts rejected INSIDE a fused iteration, with and without an
EOS landing in the fused continuation — plus device-draft round-outcome
patterns: hits compounding across rounds of one dispatch, a rejected
draft redrafted inside the same dispatch, and a host stop landing inside
a device-drafted emission (the ring-rollback world).

Invariant: the emitted stream is ALWAYS a prefix of the synchronous
reference stream, the cursor always equals prompt + written tokens, and
every quiescent finished state equals the reference exactly — any
dispatch/commit/late-stop/rollback interleaving must leave
``num_computed_tokens`` equal to the synchronous trace.
"""

from __future__ import annotations

from dataclasses import dataclass, replace
from typing import Any, Callable

from tools.dynacheck import config as C
from tools.dynacheck.explore import Model

PROMPT_LEN = 2
MAX_TOKENS = 6
EOS = 9
HOST_STOP = 5


@dataclass(frozen=True)
class _World:
    """Token oracle parameters: where the device-watched EOS and the
    host-only stop land in the generated stream (1-based generation
    index), which drafted positions a verify step gets right, and the
    per-round outcomes of on-device ring drafting ("hit" = the ring
    match replays the target, "miss" = no match or rejected draft —
    both degenerate to the plain scanned decode token)."""
    eos_at: int | None
    host_at: int | None
    draft_hits: tuple[bool, ...] = (True, False)
    dd_pattern: tuple[str, ...] = ()

    def token(self, n: int) -> int:
        # n = generation index of the token being sampled (1-based past
        # the prefill token). Values are distinct from EOS/HOST_STOP
        # unless the world places one there.
        if self.eos_at is not None and n == self.eos_at:
            return EOS
        if self.host_at is not None and n == self.host_at:
            return HOST_STOP
        return 10 + (n % 4)


@dataclass(frozen=True)
class _Plan:
    """One in-flight planned step (the model's _PlannedStep)."""
    kind: str                 # "chain" | "verify"
    n_steps: int              # device iterations dispatched
    outputs: tuple[int, ...]  # device-produced tokens (with stop padding)
    adv_proc: int             # optimistic processed overlay
    adv_gen: int              # optimistic generated overlay
    deterministic: bool = True
    draft: tuple[int, ...] = ()


@dataclass(frozen=True)
class _State:
    world: _World
    processed: int = PROMPT_LEN    # K/V written (prompt; pending not yet)
    generated: int = 1             # prefill sampled token counts as 1
    pending: int | None = None     # set in __post_init__ via factory
    emitted: tuple[int, ...] = ()
    finished: str | None = None    # "eos" | "host" | "length" | "cancel"
    inflight: _Plan | None = None
    verify_round: int = 0          # which draft_hits entry the next verify uses
    dd_round: int = 0              # which dd_pattern entry the next device round uses

    # Effective (overlay) cursors — what plan-time reads see.
    @property
    def eff_processed(self) -> int:
        return self.processed + (self.inflight.adv_proc if self.inflight else 0)

    @property
    def eff_generated(self) -> int:
        return self.generated + (self.inflight.adv_gen if self.inflight else 0)


def _initial(world: _World) -> _State:
    # The prefill sampled token(0): generation index 0.
    return _State(world=world, pending=world.token(0))


def _device_outputs(world: _World, gen0: int, n_steps: int) -> tuple[int, ...]:
    """What the device megastep produces: per inner iteration i it samples
    token(gen0 + i); once a watched EOS is sampled the lane goes dead and
    pads the remaining outputs with its last live token."""
    out: list[int] = []
    dead_pad: int | None = None
    for i in range(n_steps):
        if dead_pad is not None:
            out.append(dead_pad)
            continue
        t = world.token(gen0 + i)
        out.append(t)
        if t == EOS:
            dead_pad = t
    return tuple(out)


def _scan_stop(state: _State, toks: tuple[int, ...]) -> tuple[int, str | None]:
    """Host stop scan (the authority): accept tokens until EOS, the
    host-only stop, or the generation budget; k = accepted count."""
    for j, t in enumerate(toks):
        gen_after = state.generated + j + 1
        if t == EOS:
            return j + 1, "eos"
        if t == HOST_STOP:
            return j + 1, "host"
        if gen_after >= MAX_TOKENS:
            return j + 1, "length"
    return len(toks), None


def _commit(state: _State) -> _State:
    """Land the in-flight step: stop scan, cursor advance (k of the
    optimistic n may land — the rollback IS the cursor), emission."""
    plan = state.inflight
    if plan is None:
        return state
    if state.finished is not None:
        # Zombie lane: the optimistic chain is discarded wholesale.
        return replace(state, inflight=None)
    k, finish = _scan_stop(state, plan.outputs)
    accepted = plan.outputs[:k]
    new = replace(
        state,
        inflight=None,
        processed=state.processed + k,
        generated=state.generated + k,
        emitted=state.emitted + accepted,
        pending=accepted[-1] if finish is None else None,
        finished=finish,
    )
    return new


class CursorModel(Model):
    name = "cursor"
    max_depth = C.MODEL_DEPTHS["cursor"]

    def initial_states(self):
        worlds = [
            ("plain", _World(eos_at=None, host_at=None)),
            ("eos-mid-megastep", _World(eos_at=2, host_at=None)),
            ("host-stop-early", _World(eos_at=None, host_at=2)),
            ("host-before-eos", _World(eos_at=4, host_at=3)),
            ("eos-at-boundary", _World(eos_at=3, host_at=None,
                                       draft_hits=(False, True))),
            # ISSUE 12 worlds: drafts rejected INSIDE a fused iteration —
            # the on-device rollback (correction token + scanned
            # continuation) must replay the synchronous trace exactly,
            # including an EOS sampled by the continuation right after a
            # rejection and a host-only stop the device cannot see.
            ("reject-inside-fused", _World(eos_at=None, host_at=None,
                                           draft_hits=(False, False))),
            ("reject-then-eos", _World(eos_at=3, host_at=None,
                                       draft_hits=(False,))),
            ("reject-then-host-stop", _World(eos_at=None, host_at=2,
                                             draft_hits=(False, True))),
            # ISSUE 18 worlds: on-device ring drafting. No host verify
            # rows (draft_hits=()) — the dd lane is its own drafter.
            ("device-draft-extend", _World(eos_at=None, host_at=None,
                                           draft_hits=(),
                                           dd_pattern=("hit", "hit"))),
            ("device-reject-then-redraft", _World(eos_at=None, host_at=None,
                                                  draft_hits=(),
                                                  dd_pattern=("miss", "hit"))),
            ("device-ring-rollback-after-host-stop",
             _World(eos_at=None, host_at=2, draft_hits=(),
                    dd_pattern=("hit", "hit"))),
            ("device-draft-into-eos", _World(eos_at=2, host_at=None,
                                             draft_hits=(),
                                             dd_pattern=("hit",))),
        ]
        for label, w in worlds:
            yield f"init:{label}", _initial(w)

    def actions(self, state: _State) -> list[tuple[str, Callable[[Any], Any]]]:
        acts: list[tuple[str, Callable[[Any], Any]]] = []
        blocked = state.inflight is not None and not state.inflight.deterministic
        can_plan = (
            state.finished is None
            and not blocked
            and not self._finishes_inflight(state)
        )
        if can_plan:
            if state.inflight is None:
                acts.append(("step_sync", self._step_sync))
            acts.append(("step_async_k1", lambda s: self._step_async(s, 1)))
            acts.append(("step_async_k2", lambda s: self._step_async(s, 2)))
            if state.verify_round < len(state.world.draft_hits):
                acts.append(("step_verify", self._step_verify))
                acts.append(("step_fused_verify", self._step_fused_verify))
            if state.dd_round < len(state.world.dd_pattern):
                acts.append(("step_device_draft", self._step_device_draft))
        if state.inflight is not None:
            acts.append(("drain", lambda s: _commit(s)))
            acts.append(("cancel", self._cancel))
        acts.sort(key=lambda kv: kv[0])
        return acts

    # The engine's _decode_candidates excludes lanes whose in-flight step
    # is guaranteed to finish them (generation budget / context edge) —
    # mirrored here so the model only plans what the engine would.
    @staticmethod
    def _finishes_inflight(state: _State) -> bool:
        return state.eff_generated >= MAX_TOKENS

    @staticmethod
    def _plan(state: _State, k: int) -> _Plan:
        outputs = _device_outputs(state.world, state.eff_generated, k)
        return _Plan(
            kind="chain", n_steps=k, outputs=outputs,
            adv_proc=k, adv_gen=k,
        )

    def _step_sync(self, state: _State) -> _State:
        return _commit(replace(state, inflight=self._plan(state, 1)))

    def _step_async(self, state: _State, k: int) -> _State:
        """The one-step-ahead order (_step_async): plan N+1 against the
        overlay FIRST, then commit step N."""
        new_plan = self._plan(state, k)
        committed = _commit(state)
        return replace(committed, inflight=new_plan)

    def _step_verify(self, state: _State) -> _State:
        """Speculative verify step: pending + 1 drafted token as one row.
        The draft is right or wrong per the world's acceptance pattern;
        a wrong draft's K/V write sits past the cursor and is rolled
        back by it. Data-dependent advance -> non-deterministic plan:
        the explorer cannot plan over it (like the engine's barrier)."""
        hit = state.world.draft_hits[state.verify_round]
        gen0 = state.eff_generated
        target0 = state.world.token(gen0)      # target's choice at slot 0
        target1 = state.world.token(gen0 + 1)  # choice after an accepted draft
        draft = (target0,) if hit else (target0 + 100,)
        # The device verifies pending+draft and returns the target's own
        # counter-keyed choices for each position.
        outputs = (target0, target1) if hit else (target0,)
        new_plan = _Plan(
            kind="verify", n_steps=1 + len(draft), outputs=outputs,
            adv_proc=1, adv_gen=1, deterministic=False, draft=draft,
        )
        committed = _commit(state)
        return replace(
            committed, inflight=new_plan,
            verify_round=state.verify_round + 1,
        )

    def _step_fused_verify(self, state: _State) -> _State:
        """The UNIVERSAL megastep (ISSUE 12): one dispatch fuses the
        verify row with a scanned decode continuation. Accept/reject
        resolves on device — iteration 0 emits accepted + 1 tokens
        (the last is the target's correction/bonus choice; a rejected
        draft's K/V write sits past the cursor and the continuation
        overwrites it in place) — then the remaining inner iteration
        decodes from the resolved token. The combined emission is a
        plain chain over the target's own counter-keyed choices, so the
        commit is exactly the megastep stop-scan; the advance stays
        data-dependent, so the plan is non-deterministic and the next
        plan is barred until it commits (the engine's barrier)."""
        hit = state.world.draft_hits[state.verify_round]
        gen0 = state.eff_generated
        target0 = state.world.token(gen0)
        draft = (target0,) if hit else (target0 + 100,)
        # Iteration-0 emission (accepted + 1) plus ONE scanned decode
        # iteration; EOS inside either part dead-pads the rest, exactly
        # like _device_outputs' megastep contract.
        n_out = (2 if hit else 1) + 1
        outputs = _device_outputs(state.world, gen0, n_out)
        new_plan = _Plan(
            kind="fused-verify", n_steps=2, outputs=outputs,
            adv_proc=1, adv_gen=1, deterministic=False, draft=draft,
        )
        committed = _commit(state)
        return replace(
            committed, inflight=new_plan,
            verify_round=state.verify_round + 1,
        )

    def _step_device_draft(self, state: _State) -> _State:
        """ON-DEVICE n-gram drafting (ISSUE 18): one dispatch runs inner
        iteration 0 (the plain decode row, one token) then up to two
        draft->verify->accept rounds drafted from the device-resident
        history ring BETWEEN inner iterations. A "hit" round's ring
        match replays the target's choice, so the round lands the
        accepted draft plus the bonus choice (2 tokens); a "miss" round
        (no ring match, or a rejected draft whose K/V write sits past
        the cursor) degenerates to the plain scanned decode token (1).
        The whole emission is a chain over the target's own
        counter-keyed choices — bit-identity holds regardless of draft
        quality — so the commit is exactly the megastep stop-scan. A
        host-only stop inside the emission truncates it, and because the
        next plan's outputs are computed from the POST-COMMIT cursor,
        the model encodes the ring-rollback contract: after a host
        truncation the ring is repacked from committed truth, never from
        the device's optimistic tail. Data-dependent advance -> the
        plan is non-deterministic and bars the next plan (the barrier).
        """
        remaining = len(state.world.dd_pattern) - state.dd_round
        rounds = state.world.dd_pattern[
            state.dd_round: state.dd_round + min(2, remaining)]
        gen0 = state.eff_generated
        n_out = 1 + sum(2 if r == "hit" else 1 for r in rounds)
        outputs = _device_outputs(state.world, gen0, n_out)
        new_plan = _Plan(
            kind="device-draft", n_steps=1 + len(rounds), outputs=outputs,
            adv_proc=1, adv_gen=1, deterministic=False,
        )
        committed = _commit(state)
        return replace(
            committed, inflight=new_plan,
            dd_round=state.dd_round + len(rounds),
        )

    @staticmethod
    def _cancel(state: _State) -> _State:
        if state.finished is not None:
            return replace(state, inflight=None)
        return replace(state, finished="cancel", inflight=None,
                       pending=None)

    # -- invariants --------------------------------------------------------

    def invariants(self, state: _State) -> list[str]:
        out: list[str] = []
        ref_emitted, ref_processed, ref_finish = _reference(state.world)
        n = len(state.emitted)
        if state.emitted != ref_emitted[:n]:
            out.append(
                f"stream diverged from the synchronous trace: emitted "
                f"{state.emitted}, reference {ref_emitted[:n]}"
            )
        # num_computed_tokens == prompt + accepted writes, always.
        if state.processed != PROMPT_LEN + n:
            out.append(
                f"cursor drift: processed={state.processed}, but prompt "
                f"{PROMPT_LEN} + emitted {n} = {PROMPT_LEN + n}"
            )
        if state.generated != 1 + n:
            out.append(
                f"generated drift: {state.generated} != 1 + emitted {n}"
            )
        if state.processed > PROMPT_LEN + MAX_TOKENS:
            out.append(
                f"cursor past the block table: processed={state.processed}"
            )
        if state.finished is not None and state.finished != "cancel":
            if state.inflight is None and (
                state.emitted != ref_emitted
                or state.processed != ref_processed
                or state.finished != ref_finish
            ):
                out.append(
                    "finished state diverges from the synchronous trace: "
                    f"emitted={state.emitted} vs {ref_emitted}, "
                    f"processed={state.processed} vs {ref_processed}, "
                    f"finish={state.finished} vs {ref_finish}"
                )
        return out

    def fingerprint(self, state: _State) -> Any:
        return (
            state.world,
            state.processed, state.generated, state.pending,
            state.emitted, state.finished, state.inflight,
            state.verify_round, state.dd_round,
        )


def _reference(world: _World) -> tuple[tuple[int, ...], int, str]:
    """The synchronous k=1, no-speculation trace: the bit-identical
    baseline every interleaving must reproduce."""
    state = _initial(world)
    while state.finished is None:
        state = _commit(replace(state, inflight=_Plan(
            kind="chain", n_steps=1,
            outputs=_device_outputs(world, state.generated, 1),
            adv_proc=1, adv_gen=1,
        )))
    return state.emitted, state.processed, state.finished


# ---------------------------------------------------------------------------
# pp wavefront model (ISSUE 20): commit ordering across in-flight
# microbatch groups inside ONE fused pipeline-parallel dispatch.
# ---------------------------------------------------------------------------

PP_STAGES = 2
PP_GROUPS = 2          # M microbatch groups riding the stage ring
PP_MAX_TOKENS = 4


@dataclass(frozen=True)
class _PPWorld:
    """Per-group token oracles for the wavefront world. Each group's
    next token CHAINS from the previous sampled token (feedback) — the
    value stage 0 embeds for iteration t+1 is only correct if iteration
    t's drain (sampling on the last stage) is already visible. That
    visibility is exactly what the wavefront barrier guarantees: with M
    groups interleaved over pp stages and M >= pp, the drain of (t, g)
    at round t*M + g + pp - 1 strictly precedes the entry of (t+1, g)
    at round (t+1)*M + g."""
    eos_at: tuple[int | None, ...]
    host_at: tuple[int | None, ...]

    def token(self, g: int, prev: int, n: int) -> int:
        if self.eos_at[g] is not None and n == self.eos_at[g]:
            return EOS
        if self.host_at[g] is not None and n == self.host_at[g]:
            return HOST_STOP
        return 20 + ((prev * 7 + n + g) % 5)


@dataclass(frozen=True)
class _PPState:
    world: _PPWorld
    pending: tuple[int, ...] = ()      # last committed token per group
    generated: tuple[int, ...] = ()    # committed generation count
    emitted: tuple[tuple[int, ...], ...] = ()
    finished: tuple[str | None, ...] = ()


def _pp_initial(world: _PPWorld) -> _PPState:
    return _PPState(
        world=world,
        pending=tuple(10 + g for g in range(PP_GROUPS)),
        generated=(1,) * PP_GROUPS,
        emitted=((),) * PP_GROUPS,
        finished=(None,) * PP_GROUPS,
    )


def _pp_dispatch_outputs(
    state: _PPState, k: int, *, barrier: bool
) -> list[tuple[int, ...]]:
    """Simulate one fused pp dispatch: k inner iterations over M groups
    wavefronting through PP_STAGES stages. Work item (t, g) enters stage
    0 at round t*M + g and drains at round t*M + g + pp - 1; a stage-0
    entry reads the LATEST drained token (and the latest drained stop
    flag) whose drain round strictly precedes its entry round.

    ``barrier=True`` is the real schedule (M >= pp, so the previous
    iteration has always drained). ``barrier=False`` is the
    drop-the-barrier mutant: every iteration enters pp - 1 rounds early,
    BEFORE the previous drain is visible — stage 0 embeds a STALE token
    and reads a stale alive flag, exactly the bug the wavefront
    interleave exists to make impossible."""
    early = 0 if barrier else PP_STAGES - 1
    outs: list[tuple[int, ...]] = []
    for g in range(PP_GROUPS):
        # The committed pending token drained BEFORE this dispatch: it
        # is visible to any entry round, mutant or not.
        drained: list[tuple[int, int, bool]] = [
            (-(PP_STAGES + 1), state.pending[g], False)
        ]
        toks: list[int] = []
        for t in range(k):
            entry = t * PP_GROUPS + g - early
            drain = t * PP_GROUPS + g + PP_STAGES - 1
            vis = max(i for i, (dr, _, _) in enumerate(drained)
                      if dr < entry)
            _, feed, dead = drained[vis]
            if dead or state.finished[g] is not None:
                toks.append(drained[-1][1])      # dead pad
                drained.append((drain, drained[-1][1], True))
                continue
            tok = state.world.token(g, feed, state.generated[g] + t)
            toks.append(tok)
            drained.append((drain, tok, drained[-1][2] or tok == EOS))
        outs.append(tuple(toks))
    return outs


def _pp_commit(state: _PPState, outs: list[tuple[int, ...]]) -> _PPState:
    """Host commit after the dispatch: per-group stop scan (the
    authority), cursor advance, emission — the same algebra as the
    single-lane _commit, applied per microbatch group."""
    pending = list(state.pending)
    generated = list(state.generated)
    emitted = list(state.emitted)
    finished = list(state.finished)
    for g in range(PP_GROUPS):
        if finished[g] is not None:
            continue
        k, fin = 0, None
        for j, t in enumerate(outs[g]):
            if t == EOS:
                k, fin = j + 1, "eos"
                break
            if t == HOST_STOP:
                k, fin = j + 1, "host"
                break
            if generated[g] + j + 1 >= PP_MAX_TOKENS:
                k, fin = j + 1, "length"
                break
        else:
            k = len(outs[g])
        accepted = outs[g][:k]
        generated[g] += k
        emitted[g] = emitted[g] + accepted
        pending[g] = accepted[-1] if fin is None and accepted else pending[g]
        finished[g] = fin
    return replace(
        state, pending=tuple(pending), generated=tuple(generated),
        emitted=tuple(emitted), finished=tuple(finished),
    )


def _pp_reference(world: _PPWorld, g: int) -> tuple[tuple[int, ...], str]:
    """Group g's synchronous single-lane trace: the baseline every
    wavefront interleaving must reproduce token for token."""
    prev, n, out = 10 + g, 1, []
    while True:
        t = world.token(g, prev, n)
        out.append(t)
        if t == EOS:
            return tuple(out), "eos"
        if t == HOST_STOP:
            return tuple(out), "host"
        if n + 1 >= PP_MAX_TOKENS:
            return tuple(out), "length"
        prev, n = t, n + 1


class PPWavefrontModel(Model):
    """The pp megastep's cross-group commit ordering: M microbatch
    groups share one fused dispatch, and a group's iteration t+1 may
    only embed what iteration t drained. The model explores every
    k-choice / cancel interleaving of two groups with EOS and host-only
    stops at varied positions; the drop-the-barrier mutant (entering
    iterations before the previous drain is visible) feeds stale tokens
    and provably diverges from the synchronous reference."""

    name = "pp-wavefront"
    max_depth = C.MODEL_DEPTHS["pp-wavefront"]
    barrier = True      # the mutant subclass in tests flips this

    def initial_states(self):
        worlds = [
            ("plain", _PPWorld(eos_at=(None, None), host_at=(None, None))),
            ("eos-g0-mid", _PPWorld(eos_at=(2, None), host_at=(None, None))),
            ("host-g1-early", _PPWorld(eos_at=(None, None),
                                       host_at=(None, 2))),
            ("staggered-stops", _PPWorld(eos_at=(3, None), host_at=(None, 2))),
            ("both-eos", _PPWorld(eos_at=(2, 3), host_at=(None, None))),
        ]
        for label, w in worlds:
            yield f"init:{label}", _pp_initial(w)

    def actions(self, state: _PPState):
        acts: list[tuple[str, Callable[[Any], Any]]] = []
        active = [g for g in range(PP_GROUPS) if state.finished[g] is None]
        if active:
            acts.append(("megastep_k1", lambda s: self._megastep(s, 1)))
            acts.append(("megastep_k2", lambda s: self._megastep(s, 2)))
            for g in active:
                acts.append((f"cancel_g{g}",
                             lambda s, g=g: self._cancel(s, g)))
        acts.sort(key=lambda kv: kv[0])
        return acts

    def _megastep(self, state: _PPState, k: int) -> _PPState:
        outs = _pp_dispatch_outputs(state, k, barrier=self.barrier)
        return _pp_commit(state, outs)

    @staticmethod
    def _cancel(state: _PPState, g: int) -> _PPState:
        finished = list(state.finished)
        finished[g] = "cancel"
        return replace(state, finished=tuple(finished))

    def invariants(self, state: _PPState) -> list[str]:
        out: list[str] = []
        for g in range(PP_GROUPS):
            ref, ref_fin = _pp_reference(state.world, g)
            n = len(state.emitted[g])
            if state.emitted[g] != ref[:n]:
                out.append(
                    f"group {g} stream diverged from the synchronous "
                    f"trace: emitted {state.emitted[g]}, reference {ref[:n]}"
                )
            if state.generated[g] != 1 + n:
                out.append(
                    f"group {g} cursor drift: generated="
                    f"{state.generated[g]} != 1 + emitted {n}"
                )
            fin = state.finished[g]
            if fin is not None and fin != "cancel":
                if state.emitted[g] != ref or fin != ref_fin:
                    out.append(
                        f"group {g} finished state diverges: emitted="
                        f"{state.emitted[g]} vs {ref}, finish={fin} vs "
                        f"{ref_fin}"
                    )
        return out

    def fingerprint(self, state: _PPState) -> Any:
        return (state.world, state.pending, state.generated,
                state.emitted, state.finished)
