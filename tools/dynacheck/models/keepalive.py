"""Keepalive model: the store client's lease keepalive + session
resurrection protocol (runtime/store/client.py) as an executable
miniature.

One lease with one leased key, explored through every interleaving of
keepalive beats (healthy, connection-refused, lease-expired), server-side
lease expiry, connection loss, reconnect completion (which must CANCEL
the old keepalive task before starting the replacement), mid-resurrection
re-put failures, and client-side revocation. The transition rules mirror
``_keepalive_loop`` / ``_reconnect_loop`` / ``lease_revoke`` line for
line.

Invariants checked at EVERY reachable state:

- **single keepalive task** — never two live keepalive tasks for one
  lease (the double-beat bug: the old task survives a reconnect and
  both hammer the server, masking real TTL misses);
- **same lease id** — every resurrection re-grants with ``want=old id``,
  so the lease the server holds is always the id the client's meta map
  is keyed by;
- **leased keys follow the lease** — while connected with a live lease,
  every key the client still considers leased is present server-side
  (a failed re-put DROPS the client entry rather than leaving it
  phantom);
- **revocation is terminal** — after ``lease_revoke`` nothing beats, and
  no resurrection path re-creates the lease;
- **resurrection converges (liveness)** — from any disconnected state
  with a pending reconnect, completing the reconnect restores: session
  up, same lease id, exactly one keepalive task, keys re-put.
"""

from __future__ import annotations

from typing import Any, Callable

from tools.dynacheck import config as C
from tools.dynacheck.explore import Model


class _State:
    def __init__(self) -> None:
        self.connected = True
        self.reconnect_pending = False
        self.revoked = False
        # Client side: lease meta registered, keepalive task count, the
        # leased key tracked in _leased_kv.
        self.meta = True
        self.tasks = 1
        self.client_key = True
        # Server side: lease alive, granted under the client's id, key
        # attached.
        self.server_lease = True
        self.same_id = True
        self.server_key = True

    def clone(self) -> "_State":
        new = _State.__new__(_State)
        new.__dict__.update(self.__dict__)
        return new


class KeepaliveModel(Model):
    name = "keepalive"
    max_depth = C.MODEL_DEPTHS["keepalive"]
    # Injection points for the fixture suite:
    #   cancel_before_restart=False leaves the old keepalive task running
    #   across a reconnect (the double-beat bug);
    #   regrant_with_want=False re-grants under a fresh server-chosen id,
    #   orphaning the client's meta map key.
    cancel_before_restart: bool = True
    regrant_with_want: bool = True

    def initial_states(self):
        yield "leased", _State()

    def actions(self, state: _State) -> list[tuple[str, Callable[[Any], Any]]]:
        acts: list[tuple[str, Callable[[Any], Any]]] = []
        if state.revoked:
            return acts
        if state.connected and state.tasks > 0:
            if state.server_lease:
                acts.append(("beat_ok", self._beat_ok))
            else:
                # The beat comes back StoreError("no such lease"): the
                # loop resurrects in place — re-grant want=id, re-put.
                acts.append(("beat_resurrect", self._beat_resurrect))
                acts.append(("beat_resurrect_reput_fails",
                             self._beat_resurrect_reput_fails))
        if state.connected:
            acts.append(("disconnect", self._disconnect))
            acts.append(("revoke", self._revoke))
        if state.server_lease:
            acts.append(("server_expire", self._server_expire))
        if state.reconnect_pending and not state.connected:
            acts.append(("reconnect_complete", self._reconnect_complete))
        acts.sort(key=lambda kv: kv[0])
        return acts

    # -- transitions (mirroring store/client.py) ---------------------------

    @staticmethod
    def _beat_ok(state: _State) -> _State:
        return state.clone()  # TTL refreshed; no protocol state moves

    def _resurrect(self, st: _State, reput_ok: bool) -> _State:
        # _keepalive_loop's StoreError branch: re-grant under the SAME id
        # (want=lease_id), then re-put every _leased_kv entry.
        st.server_lease = True
        if not self.regrant_with_want:
            st.same_id = False
        if st.client_key:
            if reput_ok:
                st.server_key = True
            else:
                st.client_key = False  # StoreError: entry dropped
        return st

    def _beat_resurrect(self, state: _State) -> _State:
        return self._resurrect(state.clone(), reput_ok=True)

    def _beat_resurrect_reput_fails(self, state: _State) -> _State:
        return self._resurrect(state.clone(), reput_ok=False)

    @staticmethod
    def _disconnect(state: _State) -> _State:
        st = state.clone()
        st.connected = False
        st.reconnect_pending = True
        # The keepalive task keeps looping (ConnectionError branch just
        # counts failures); the reconnect loop owns recovery.
        return st

    @staticmethod
    def _server_expire(state: _State) -> _State:
        st = state.clone()
        st.server_lease = False
        st.server_key = False  # lease-attached keys die with the lease
        return st

    def _reconnect_complete(self, state: _State) -> _State:
        st = state.clone()
        st.connected = True
        st.reconnect_pending = False
        if st.meta:
            # _reconnect_loop: cancel the old keepalive task, re-grant
            # want=old id, start a fresh task, re-put leased keys.
            if self.cancel_before_restart:
                st.tasks = 0
            st.tasks += 1
            st = self._resurrect(st, reput_ok=True)
        return st

    @staticmethod
    def _revoke(state: _State) -> _State:
        st = state.clone()
        st.revoked = True
        st.meta = False
        st.tasks = 0
        st.client_key = False
        st.server_lease = False
        st.server_key = False
        return st

    # -- invariants --------------------------------------------------------

    def invariants(self, state: _State) -> list[str]:
        out: list[str] = []
        if state.tasks > 1:
            out.append(
                f"{state.tasks} live keepalive tasks for one lease: the "
                "old task survived a reconnect"
            )
        if state.server_lease and not state.same_id:
            out.append(
                "lease resurrected under a different id: the client's "
                "meta map and leased-kv records point at a dead id"
            )
        if (
            state.connected
            and state.server_lease
            and state.client_key
            and not state.server_key
        ):
            out.append(
                "client considers a key leased but the server lost it: "
                "resurrection must re-put or drop the entry"
            )
        if state.revoked and (state.server_lease or state.tasks > 0):
            out.append(
                "lease revoked but still beating or alive server-side "
                f"(tasks={state.tasks}, server_lease={state.server_lease})"
            )
        # Resurrection converges: completing a pending reconnect restores
        # the session to exactly-one-task, same-id, keys-on-server.
        if state.reconnect_pending and not state.connected and state.meta:
            sim = self._reconnect_complete(state)
            if sim.tasks != 1 or not sim.same_id or (
                sim.client_key and not sim.server_key
            ):
                out.append(
                    "reconnect does not restore the lease session "
                    f"(tasks={sim.tasks}, same_id={sim.same_id}, "
                    f"key_on_server={sim.server_key})"
                )
        return out

    def fingerprint(self, state: _State) -> Any:
        return (
            state.connected, state.reconnect_pending, state.revoked,
            state.meta, min(state.tasks, 3), state.client_key,
            state.server_lease, state.same_id, state.server_key,
        )
