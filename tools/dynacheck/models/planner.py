"""Planner model: the REAL :class:`PlannerController`
(planner/controller.py) driven cycle by cycle on a virtual timeline
through every interleaving of demand levels, SLO misses and control-plane
outages, with a stub planner (plan = demand) and the recording connector.

Guard-rail invariants, accumulated per transition and checked at EVERY
reachable state:

- **scale-up cooldown** — no two scale-ups closer than the up-cooldown
  (the up-down-up flap guard's first half);
- **scale-down cooldown + hysteresis** — no two scale-downs closer than
  the down-cooldown, and every scale-down is preceded by at least
  ``down_stable_cycles`` consecutive below-target cycles, tracked by an
  independent shadow streak (not the controller's own counter);
- **bounded actuation** — the target moves at most ``max_step_up`` up /
  ``max_step_down`` down per cycle and stays inside [min, max]: a
  scale-down only ever drains one replica at a time;
- **degraded freeze** — a control-plane-dark cycle makes every pool
  ``degraded_hold``: targets unchanged, NO connector actuation, and the
  hysteresis streak frozen (an outage must not count toward a
  scale-down);
- **actuation every healthy cycle** — a non-degraded cycle reconciles
  the pool exactly once, at the standing target.
"""

from __future__ import annotations

import asyncio
import logging
from typing import Any, Callable

from dynamo_tpu.planner.controller import ControllerConfig, PlannerController
from dynamo_tpu.planner.planner_core import Observation, Plan, RecordingConnector
from tools.dynacheck import config as C
from tools.dynacheck.explore import Model

# The degraded branch warns every cycle; thousands of explored states
# would flood the log.
logging.getLogger("dynamo_tpu.planner.controller").setLevel(logging.ERROR)

POOL = "backend"
INTERVAL = 1.0
UP_CD = 2.0
DOWN_CD = 4.0
DOWN_CYCLES = 2
STEP_UP = 2
STEP_DOWN = 1
MIN_R, MAX_R = 1, 4

_CFG = ControllerConfig(
    interval_s=INTERVAL,
    scale_up_cooldown_s=UP_CD,
    scale_down_cooldown_s=DOWN_CD,
    down_stable_cycles=DOWN_CYCLES,
    max_step_up=STEP_UP,
    max_step_down=STEP_DOWN,
    queue_depth_per_replica=0.0,  # demand drives through the plan only
    shed_pressure=False,
    attainment_floor=0.92,
    min_replicas=MIN_R,
    max_replicas=MAX_R,
)

_POOL_FIELDS = (
    "target", "desired", "last_scale_up_t", "last_scale_down_t",
    "below_streak", "last_action", "last_reason",
)

_loop: asyncio.AbstractEventLoop | None = None


def _run(coro):
    global _loop
    if _loop is None:
        _loop = asyncio.new_event_loop()
    return _loop.run_until_complete(coro)


class _PlanStub:
    """plan = demand: the controller's guard rails are under test, not
    the predictor's math."""

    def compute_plan(self, obs: Observation) -> Plan:
        d = max(1, int(round(obs.request_rate)))
        return Plan(
            prefill_replicas=d, decode_replicas=d,
            predicted_rate=obs.request_rate,
            correction_prefill=1.0, correction_decode=1.0,
        )


class _State:
    def __init__(self, controller_cls: type = PlannerController):
        self.now = 0.0
        self.shadow_below = 0           # independent below-target streak
        self.violations: tuple[str, ...] = ()
        self.connector = RecordingConnector()
        self.ctrl = controller_cls(
            _PlanStub(), self.connector, pools={POOL: "max"},
            config=_CFG, clock=self._clock,
        )

    def _clock(self) -> float:
        return self.now

    def clone(self) -> "_State":
        new = _State(type(self.ctrl))
        new.now = self.now
        new.shadow_below = self.shadow_below
        new.violations = self.violations
        src, dst = self.ctrl.pools[POOL], new.ctrl.pools[POOL]
        for f in _POOL_FIELDS:
            setattr(dst, f, getattr(src, f))
        new.ctrl.cycles = self.ctrl.cycles
        return new


def _obs(rate: float, *, degraded: bool = False, slo=None) -> Observation:
    return Observation(
        request_rate=rate, mean_isl=64.0, mean_osl=32.0,
        slo_attainment=slo, control_plane_degraded=degraded,
    )


class PlannerModel(Model):
    name = "planner"
    max_depth = C.MODEL_DEPTHS["planner"]
    # Injection point for the fixture suite: a controller subclass with
    # the guard rails removed proves the invariants can fire.
    controller_cls: type = PlannerController

    def initial_states(self):
        yield "steady", _State(self.controller_cls)

    def actions(self, state: _State) -> list[tuple[str, Callable[[Any], Any]]]:
        return [
            ("cycle_degraded", lambda s: self._cycle(s, _obs(1.0, degraded=True))),
            ("cycle_demand_1", lambda s: self._cycle(s, _obs(1.0))),
            ("cycle_demand_3", lambda s: self._cycle(s, _obs(3.0))),
            ("cycle_demand_5", lambda s: self._cycle(s, _obs(5.0))),
            ("cycle_slo_miss", lambda s: self._cycle(
                s, _obs(1.0, slo={"ttft": 0.5, "tpot": 1.0}))),
        ]

    def _cycle(self, state: _State, obs: Observation) -> _State:
        st = state.clone()
        st.now += INTERVAL
        pool = st.ctrl.pools[POOL]
        prev_target = pool.target
        prev_up_t = pool.last_scale_up_t
        prev_down_t = pool.last_scale_down_t
        prev_streak = pool.below_streak
        prev_calls = len(st.connector.calls)
        bad: list[str] = []

        actions = _run(st.ctrl.cycle(obs))
        action = actions.get(POOL, "<missing>")
        calls = st.connector.calls[prev_calls:]

        if obs.control_plane_degraded:
            if action != "degraded_hold":
                bad.append(f"degraded cycle decided {action!r}")
            if pool.target != prev_target:
                bad.append(
                    f"degraded cycle moved target {prev_target}->{pool.target}"
                )
            if calls:
                bad.append(f"degraded cycle actuated: {calls}")
            if pool.below_streak != prev_streak:
                bad.append(
                    "degraded cycle advanced the hysteresis streak "
                    f"{prev_streak}->{pool.below_streak}"
                )
        else:
            if calls != [(POOL, pool.target)]:
                bad.append(
                    f"healthy cycle actuated {calls!r}, expected one "
                    f"reconcile at target {pool.target}"
                )
            delta = pool.target - prev_target
            if delta > STEP_UP or delta < -STEP_DOWN:
                bad.append(f"target moved {delta:+d} in one cycle")
            if not MIN_R <= pool.target <= MAX_R:
                bad.append(f"target {pool.target} outside [{MIN_R},{MAX_R}]")
            if action == "scale_up" and st.now - prev_up_t < UP_CD:
                bad.append(
                    f"scale-up {st.now - prev_up_t:.1f}s after the last "
                    f"(cooldown {UP_CD}s)"
                )
            if action == "scale_down":
                if st.now - prev_down_t < DOWN_CD:
                    bad.append(
                        f"scale-down {st.now - prev_down_t:.1f}s after the "
                        f"last (cooldown {DOWN_CD}s)"
                    )
                if st.shadow_below + 1 < DOWN_CYCLES:
                    bad.append(
                        "scale-down after only "
                        f"{st.shadow_below + 1} below-target cycle(s) "
                        f"(need {DOWN_CYCLES})"
                    )
            # Independent shadow streak from the desired/target trace.
            if pool.desired < prev_target:
                st.shadow_below += 1
            else:
                st.shadow_below = 0
        if bad:
            st.violations = st.violations + tuple(bad)
        return st

    def invariants(self, state: _State) -> list[str]:
        return list(state.violations)

    def fingerprint(self, state: _State) -> Any:
        pool = state.ctrl.pools[POOL]
        cap_up = min(UP_CD + INTERVAL, state.now - pool.last_scale_up_t)
        cap_down = min(DOWN_CD + INTERVAL, state.now - pool.last_scale_down_t)
        return (
            pool.target, pool.desired, pool.last_action,
            min(pool.below_streak, DOWN_CYCLES + 1),
            min(state.shadow_below, DOWN_CYCLES + 1),
            cap_up, cap_down,
            state.violations,
        )
