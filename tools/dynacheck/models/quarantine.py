"""Quarantine model: EndpointClient's lease-expiry quarantine machine
(runtime/component.py) as an executable miniature under a virtual clock.

One instance, explored through every interleaving of watch events (PUT,
lease-expiry DELETE with each egress-stats verdict, explicit DELETE),
ground-truth liveness flips, reconnect reconciliation, and due sweeps.
The real EndpointClient entangles a store session, dataplane egress and
an event loop, so — like the cursor model — this is a faithful
transcription of the decision logic rather than a drive of the class;
the transition rules mirror ``_on_discovery_event`` / ``_sweep_quarantine``
/ ``_reconcile`` line for line.

Invariants checked at EVERY reachable state:

- **explicit deregisters are honored** — after an explicit DELETE (a
  graceful drain said goodbye), the instance is neither routable nor
  quarantined until a fresh PUT re-registers it;
- **quarantine implies routable** — the grace window exists to KEEP the
  instance routable while it is probed; a quarantine entry for an
  unregistered instance is a leak;
- **bounded grace** — once the lease-expiry DELETE for a dead instance
  has been processed, the instance is either removed or quarantined with
  a due probe no further than one grace window out: no routing past
  grace to a truly-dead instance;
- **no quarantine-forever (liveness)** — from ANY state where a dead
  instance sits in quarantine, running the due sweeps (whose probes see
  the ground truth) removes it within two rounds — the exact bug shape
  a sweep that re-arms unconditionally would introduce;
- **counter sanity** — recoveries + expiries never exceed quarantine
  entries.
"""

from __future__ import annotations

from typing import Any, Callable

from tools.dynacheck import config as C
from tools.dynacheck.explore import Model

GRACE_S = 4.0
PROBE_SOON_S = 1.0


class _State:
    def __init__(self) -> None:
        self.now = 0.0
        self.live = True            # ground truth: backend process alive
        self.registered = True      # in EndpointClient.instances (routable)
        self.store_has = True       # record present in the store listing
        self.quarantine_due = None  # due time in _quarantine, or None
        self.lease_lost = False     # lease-expiry DELETE processed, no PUT since
        self.explicit_pending = False  # explicit DELETE processed, no PUT since
        self.quarantined_total = 0
        self.recovered_total = 0
        self.expired_total = 0

    def clone(self) -> "_State":
        new = _State.__new__(_State)
        new.__dict__.update(self.__dict__)
        return new


class QuarantineModel(Model):
    name = "quarantine"
    max_depth = C.MODEL_DEPTHS["quarantine"]
    # Injection point for the fixture suite: True makes the due sweep
    # re-arm even when the probe says dead — the quarantine-forever bug.
    sweep_rearms_dead: bool = False

    def initial_states(self):
        yield "registered", _State()

    def actions(self, state: _State) -> list[tuple[str, Callable[[Any], Any]]]:
        acts: list[tuple[str, Callable[[Any], Any]]] = [
            ("ev_put", self._ev_put),
        ]
        if state.live:
            acts.append(("kill", self._kill))
        else:
            acts.append(("revive", self._revive))
        if state.registered:
            # Lease-expiry DELETE: the egress-stats judge can say
            # connected (possibly stale), breaker-open, or nothing.
            acts.append(("ev_lease_judged_up", self._lease_up))
            acts.append(("ev_lease_judged_down", self._lease_down))
            acts.append(("ev_lease_judged_unknown", self._lease_unknown))
        if state.registered or state.quarantine_due is not None:
            acts.append(("ev_explicit_delete", self._explicit))
        if state.quarantine_due is not None:
            acts.append(("sweep_due", self._sweep_due))
        if state.registered and not state.store_has:
            acts.append(("reconcile_missing", self._reconcile))
        acts.sort(key=lambda kv: kv[0])
        return acts

    # -- transitions (mirroring component.py) ------------------------------

    @staticmethod
    def _ev_put(state: _State) -> _State:
        st = state.clone()
        st.store_has = True
        st.registered = True
        st.lease_lost = False
        st.explicit_pending = False
        if st.quarantine_due is not None:
            st.quarantine_due = None
            st.recovered_total += 1
        return st

    @staticmethod
    def _kill(state: _State) -> _State:
        st = state.clone()
        st.live = False
        return st

    @staticmethod
    def _revive(state: _State) -> _State:
        st = state.clone()
        st.live = True
        return st

    def _lease_expired(self, state: _State, judged) -> _State:
        st = state.clone()
        st.store_has = False
        st.lease_lost = True
        if judged is False:
            return self._remove(st)
        delay = GRACE_S if judged else PROBE_SOON_S
        if st.quarantine_due is None:
            st.quarantined_total += 1
        st.quarantine_due = st.now + delay
        return st

    def _lease_up(self, state: _State) -> _State:
        return self._lease_expired(state, True)

    def _lease_down(self, state: _State) -> _State:
        return self._lease_expired(state, False)

    def _lease_unknown(self, state: _State) -> _State:
        return self._lease_expired(state, None)

    def _explicit(self, state: _State) -> _State:
        st = state.clone()
        st.store_has = False
        st.explicit_pending = True
        return self._remove(st)

    @staticmethod
    def _remove(st: _State) -> _State:
        st.registered = False
        st.quarantine_due = None
        st.lease_lost = False
        return st

    def _sweep_due(self, state: _State) -> _State:
        # The sweep task wakes at the due time and probes; the probe is a
        # real dial, so it sees the ground truth.
        st = state.clone()
        st.now = max(st.now, st.quarantine_due)
        if st.live or self.sweep_rearms_dead:
            st.quarantine_due = st.now + GRACE_S
        else:
            st.expired_total += 1
            self._remove(st)
        return st

    def _reconcile(self, state: _State) -> _State:
        # Reconnect reconciliation: a cached instance missing from the
        # listing is probed; alive → quarantined, dead → removed.
        st = state.clone()
        if st.live:
            if st.quarantine_due is None:
                st.quarantined_total += 1
                st.quarantine_due = st.now + GRACE_S
        else:
            self._remove(st)
        return st

    # -- invariants --------------------------------------------------------

    def invariants(self, state: _State) -> list[str]:
        out: list[str] = []
        if state.explicit_pending and (
            state.registered or state.quarantine_due is not None
        ):
            out.append(
                "explicit deregister not honored: instance still "
                f"registered={state.registered}, "
                f"quarantined={state.quarantine_due is not None}"
            )
        if state.quarantine_due is not None and not state.registered:
            out.append(
                "quarantine entry for an unregistered instance: the grace "
                "window exists to keep it routable while probed"
            )
        if state.lease_lost and not state.live and state.registered:
            if state.quarantine_due is None:
                out.append(
                    "dead instance routable after lease expiry with no "
                    "quarantine tracking: nothing will ever remove it"
                )
            elif state.quarantine_due - state.now > GRACE_S:
                out.append(
                    "dead instance routable with a probe scheduled past "
                    f"one grace window ({state.quarantine_due - state.now:.1f}s "
                    f"> {GRACE_S}s)"
                )
        # No quarantine-forever (liveness): a dead quarantined instance
        # must be removed within two due sweeps.
        if state.quarantine_due is not None and not state.live:
            sim = state.clone()
            for _ in range(2):
                if sim.quarantine_due is None:
                    break
                sim = self._sweep_due(sim)
            if sim.quarantine_due is not None:
                out.append(
                    "dead instance quarantined forever: two due sweeps "
                    "with failing probes did not remove it"
                )
        if state.recovered_total + state.expired_total > state.quarantined_total:
            out.append(
                f"counter drift: recovered={state.recovered_total} + "
                f"expired={state.expired_total} > "
                f"quarantined={state.quarantined_total}"
            )
        return out

    def fingerprint(self, state: _State) -> Any:
        due = (
            None if state.quarantine_due is None
            else min(GRACE_S, state.quarantine_due - state.now)
        )
        return (
            state.live, state.registered, state.store_has,
            state.lease_lost, state.explicit_pending, due,
            min(state.quarantined_total, 3),
            min(state.recovered_total, 3),
            min(state.expired_total, 3),
        )
