"""Deterministic report assembly: same tree -> byte-identical report.

No timestamps, no runtimes, no absolute paths in the default report —
the determinism test in tests/test_dynacheck.py diffs two full runs
byte for byte, and CI diffs against cache hits.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from tools.dynacheck.callgraph import Project
from tools.dynacheck.explore import ModelResult
from tools.dynacheck.interproc import Finding


@dataclass
class Report:
    findings: list[Finding] = field(default_factory=list)
    models: list[ModelResult] = field(default_factory=list)
    functions: int = 0
    resolved_edges: int = 0
    pragmas: list = field(default_factory=list)

    @property
    def ok(self) -> bool:
        return not self.findings and all(m.ok for m in self.models)

    def render(self, show_pragmas: bool = False) -> str:
        lines: list[str] = []
        for f in self.findings:
            lines.append(str(f))
        for m in self.models:
            lines.append(m.summary())
            for v in m.violations:
                lines.append(f"  {v}")
        if show_pragmas:
            for p in sorted(self.pragmas, key=lambda p: (p.path, p.line)):
                lines.append(f"pragma: {p.path}:{p.line}: allow-{p.rule}({p.reason})")
        n = len(self.findings)
        viol = sum(len(m.violations) for m in self.models)
        lines.append(
            f"dynacheck: {self.functions} functions, "
            f"{self.resolved_edges} resolved call edges; "
            f"{n} finding{'s' if n != 1 else ''}, "
            f"{viol} model violation{'s' if viol != 1 else ''}, "
            f"{len(self.pragmas)} pragma{'s' if len(self.pragmas) != 1 else ''}"
        )
        return "\n".join(lines) + "\n"


def stats_for(project: Project) -> tuple[int, int]:
    functions = len(project.functions)
    edges = sum(
        1 for f in project.functions.values() for cs in f.calls if cs.targets
    )
    return functions, edges
