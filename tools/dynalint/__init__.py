"""dynalint — project-native static analysis for dynamo-tpu.

AST-based (stdlib ``ast`` + ``tokenize`` only, no third-party deps) lints
tuned to the failure modes of a long-running async serving stack:

- ``fire-and-forget-task``: ``asyncio.create_task`` whose Task is dropped
  on the floor (exceptions vanish; the loop logs them only at gc time).
- ``blocking-in-async``: synchronous sleeps / file / socket / subprocess
  calls on the event loop.
- ``broad-except``: ``except Exception`` / bare ``except`` that neither
  logs, re-raises, nor carries an allow pragma with a reason.
- ``lock-discipline``: attributes registered in ``config.GUARDED_BY``
  mutated outside a ``with <lock>`` scope.
- ``jax-pitfall``: jax/jnp work in ``__del__``/signal handlers, ``jit``
  over bound-state closures, prints/self-mutation under trace.

Run as ``python -m tools.dynalint dynamo_tpu/ tests/`` or through
``tests/test_dynalint.py`` (tier-1).

Suppression pragmas (reason required, enforced):

    # dynalint: allow-<rule>(<reason>)      on the finding line or the line above
    # dynalint: holds-lock(<lockname>)      on a def line: caller holds the lock
"""

from tools.dynalint.linter import Finding, Pragma, lint_file, lint_paths

__all__ = ["Finding", "Pragma", "lint_file", "lint_paths"]
