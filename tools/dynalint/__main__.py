"""CLI: ``python -m tools.dynalint dynamo_tpu/ tests/``.

Exit 0 when the tree is clean, 1 when there are findings, 2 on usage
errors. ``--rules`` narrows to a comma-separated subset; ``--pragmas``
prints the in-source suppression inventory (what tests/test_dynalint.py
pins in its grandfather table).
"""

from __future__ import annotations

import argparse
import sys
from pathlib import Path

from tools.dynalint import config as C
from tools.dynalint.linter import lint_paths


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.dynalint",
        description="dynamo-tpu project-native static analysis",
    )
    ap.add_argument("paths", nargs="+", help="files or directories to lint")
    ap.add_argument(
        "--rules", default=None,
        help=f"comma-separated subset of: {', '.join(C.ALL_RULES)}",
    )
    ap.add_argument(
        "--pragmas", action="store_true",
        help="also list every dynalint suppression pragma in the tree",
    )
    args = ap.parse_args(argv)

    rules = None
    if args.rules:
        rules = {r.strip() for r in args.rules.split(",") if r.strip()}
        unknown = rules - set(C.ALL_RULES)
        if unknown:
            print(f"unknown rules: {', '.join(sorted(unknown))}", file=sys.stderr)
            return 2

    paths = [Path(p) for p in args.paths]
    for p in paths:
        if not p.exists():
            print(f"no such path: {p}", file=sys.stderr)
            return 2

    repo_root = Path(__file__).resolve().parents[2]
    result = lint_paths(paths, repo_root)
    findings = result.findings
    if rules is not None:
        # Pragma/syntax errors always surface: they mean the tree lies.
        findings = [
            f for f in findings
            if f.rule in rules or f.rule in ("malformed-pragma", "syntax-error")
        ]

    for f in findings:
        print(f)
    if args.pragmas:
        for p in sorted(result.pragmas, key=lambda p: (p.path, p.line)):
            print(f"pragma: {p}")
    n = len(findings)
    print(f"dynalint: {n} finding{'s' if n != 1 else ''}, "
          f"{len(result.pragmas)} pragma{'s' if len(result.pragmas) != 1 else ''}")
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
