"""dynalint configuration: rule tables and the GUARDED_BY registry.

Everything here is data, not code — the linter (``linter.py``) is generic
and this file pins it to the dynamo-tpu codebase.
"""

from __future__ import annotations

# ---------------------------------------------------------------------------
# Rule ids (used in pragmas: `# dynalint: allow-<rule>(<reason>)`)
# ---------------------------------------------------------------------------

RULE_FIRE_AND_FORGET = "fire-and-forget-task"
RULE_BLOCKING_IN_ASYNC = "blocking-in-async"
RULE_BROAD_EXCEPT = "broad-except"
RULE_LOCK_DISCIPLINE = "lock-discipline"
RULE_JAX_PITFALL = "jax-pitfall"
RULE_UNCLOSED_SPAN = "unclosed-span"
RULE_HOST_SYNC = "blocking-host-sync"
RULE_UNBOUNDED_AWAIT = "unbounded-await"

ALL_RULES = (
    RULE_FIRE_AND_FORGET,
    RULE_BLOCKING_IN_ASYNC,
    RULE_BROAD_EXCEPT,
    RULE_LOCK_DISCIPLINE,
    RULE_JAX_PITFALL,
    RULE_UNCLOSED_SPAN,
    RULE_HOST_SYNC,
    RULE_UNBOUNDED_AWAIT,
)

# ---------------------------------------------------------------------------
# blocking-in-async: dotted call names that block the event loop.
# Key is the full dotted name as written at the call site (after resolving
# the attribute chain textually — no import tracking; these modules are
# conventionally imported under their own names in this repo).
# ---------------------------------------------------------------------------

BLOCKING_CALLS = {
    "time.sleep": "time.sleep() blocks the event loop; use await asyncio.sleep()",
    "subprocess.run": "subprocess.run() blocks; use asyncio.create_subprocess_exec or asyncio.to_thread",
    "subprocess.call": "subprocess.call() blocks; use asyncio.create_subprocess_exec or asyncio.to_thread",
    "subprocess.check_call": "subprocess.check_call() blocks; use asyncio.to_thread",
    "subprocess.check_output": "subprocess.check_output() blocks; use asyncio.to_thread",
    "os.system": "os.system() blocks; use asyncio.create_subprocess_shell",
    "socket.create_connection": "sync socket connect blocks; use asyncio.open_connection",
    "socket.getaddrinfo": "sync DNS resolution blocks; use loop.getaddrinfo",
    "urllib.request.urlopen": "sync HTTP blocks; use an async client or asyncio.to_thread",
}

# Any call rooted at `requests.` (requests.get/post/Session()...) blocks.
BLOCKING_ROOTS = {
    "requests": "requests.* is synchronous HTTP; use asyncio.to_thread or an async client",
}

# ---------------------------------------------------------------------------
# lock-discipline: the GUARDED_BY registry.
#
# Maps repo-relative file -> {(scope, attr): lock}.
#   scope  — class name owning the attribute, or None for module globals.
#   lock   — name of the lock attribute (`self.<lock>` for class scopes,
#            bare `<lock>` for module scope) that must be held (lexically
#            inside `with`/`async with`, or declared via a
#            `# dynalint: holds-lock(<lock>)` pragma on the enclosing def)
#            when the attribute is MUTATED. Reads are not checked.
#            The sentinel EXTERNAL documents attributes synchronized by a
#            lock the owning object cannot see (checked by convention and
#            review, not by this linter).
#
# `__init__` (and module top level for module globals' initial binding) is
# exempt: nothing else can hold a reference during construction.
# ---------------------------------------------------------------------------

EXTERNAL = "<external>"

GUARDED_BY = {
    "dynamo_tpu/engine/core.py": {
        # add_request() is documented as callable from any thread.
        ("EngineCore", "_req_counter"): "_lock",
        # Held-block bookkeeping is touched by the disagg transfer
        # endpoints (server thread) and by step() (engine thread).
        ("EngineCore", "_held"): "_step_lock",
        ("EngineCore", "_held_deadline"): "_step_lock",
        ("EngineCore", "transfer_stats"): "_step_lock",
    },
    "dynamo_tpu/engine/block_allocator.py": {
        # DeviceBlockAllocator is externally synchronized: every caller
        # reaches it through EngineCore under _step_lock (engine/core.py).
        ("DeviceBlockAllocator", "_free"): EXTERNAL,
        ("DeviceBlockAllocator", "_by_hash"): EXTERNAL,
        ("DeviceBlockAllocator", "_inactive"): EXTERNAL,
        ("DeviceBlockAllocator", "_partials"): EXTERNAL,
    },
    "dynamo_tpu/engine/fair_queue.py": {
        # The per-tenant DRR admission queue (ISSUE 10) is externally
        # synchronized like the allocator: EngineCore reaches it only
        # under _step_lock (intake goes through the thread-safe _inbox
        # deque), the mocker only from its single sim loop.
        ("FairQueue", "_queues"): EXTERNAL,
        ("FairQueue", "_deficits"): EXTERNAL,
        ("FairQueue", "_order"): EXTERNAL,
    },
    "dynamo_tpu/llm/kv_router/native_radix.py": {
        # One-shot lazy .so build+load, raced by every router thread.
        (None, "_lib"): "_lock",
        (None, "_load_failed"): "_lock",
    },
    "dynamo_tpu/llm/kv_pool/global_index.py": {
        # Single-writer discipline like the radix tree it wraps: only the
        # indexer's event task mutates the tier ledger; readers share its
        # event loop (kv_router/indexer.py docstring).
        ("GlobalKvIndex", "_tiers"): EXTERNAL,
        ("GlobalKvIndex", "_last_event_id"): EXTERNAL,
        ("GlobalKvIndex", "_fwd_id"): EXTERNAL,
    },
    "dynamo_tpu/llm/kv_router/publisher.py": {
        # Bounded event buffer: every mutation is loop-affine (engine
        # threads hop in via call_soon_threadsafe; one drain task pops).
        ("KvEventPublisher", "_buf"): EXTERNAL,
    },
    "dynamo_tpu/obs/snapshot.py": {
        # Bounded snapshot buffer (ISSUE 13): loop-affine like the KV
        # event publisher — the tick task enqueues, the single drain
        # task pops, both on one event loop.
        ("SnapshotPublisher", "_snapbuf"): EXTERNAL,
    },
    "dynamo_tpu/runtime/component.py": {
        # Degraded-mode quarantine buffer (ISSUE 15): lease-expiry
        # deletes held while the data plane answers. Loop-affine — the
        # watch loop, the quarantine sweep, and the reconnect reconcile
        # all run on the client's one event loop.
        ("EndpointClient", "_quarantine"): EXTERNAL,
    },
    "dynamo_tpu/llm/discovery.py": {
        # Deferred last-instance model removals (ISSUE 15): same
        # loop-affinity as the quarantine buffer (watch loop + sweep).
        ("ModelWatcher", "_deferred"): EXTERNAL,
    },
}

# Mutating method names: `x.<name>(...)` counts as a mutation of `x`.
MUTATOR_METHODS = {
    "append", "extend", "insert", "add", "update", "setdefault",
    "pop", "popleft", "popitem", "remove", "discard", "clear",
    "appendleft", "rotate", "sort", "reverse",
}

# ---------------------------------------------------------------------------
# blocking-host-sync: device->host synchronization points flagged inside
# step-loop HOT PATHS (the plan/dispatch side of the async pipelined
# engine, PERF.md r8). A blocking sync there serializes host work with
# device compute — exactly the overhead the one-step-ahead loop removes;
# landings belong on the commit side. Suppress an intentional sync with a
# `# dynalint: sync-ok` pragma on the line (or the line above) — e.g. the
# double-buffered landing point itself, or np.asarray over a host list.
# ---------------------------------------------------------------------------

# Call names (last dotted component) that block on device state.
HOST_SYNC_FNS = {"fetch_replicated", "fetch_replicated_many", "device_get"}

# Method-style syncs: `x.item()` / `x.block_until_ready()` on any receiver.
HOST_SYNC_METHODS = {"item", "block_until_ready"}

# `np.asarray` / `numpy.asarray` (D2H landing when handed a device array).
HOST_SYNC_ASARRAY_ROOTS = {"np", "numpy"}

# Hot-path registry: repo-relative file suffix -> function names whose
# bodies must stay sync-free. Nested defs (commit closures) are NOT hot —
# the commit side is where landings belong.
HOT_STEP_FUNCS: dict[str, set[str]] = {
    "dynamo_tpu/engine/core.py": {
        "_plan_step", "_plan_waves", "_plan_prefill_wave", "_plan_decode",
        "_plan_megastep", "_plan_verify", "_plan_mixed", "_plan_fused",
        "_merge_plans", "_dispatch_ragged", "_dispatch_megastep",
        "_dispatch_fused", "_assemble_ragged", "_grow_or_preempt",
        "_admit", "land",
        # pp fast path (ISSUE 20): the fused pipeline device bodies — a
        # host sync inside either would land INSIDE the traced wavefront
        # scan and serialize every stage hop.
        "_pp_prefill_and_sample", "_pp_decode_chain",
    },
    # pp microbatch planning (ISSUE 20): runs on the plan side of every
    # pipelined step — a device sync here stalls the stage ring exactly
    # like one in _plan_megastep would.
    "dynamo_tpu/parallel/pipeline.py": {"plan_microbatches"},
    # Detector fixtures (linted directly by tests; excluded from the tree).
    "tests/fixtures/dynalint/host_sync_bad.py": {"plan_step", "dispatch"},
    "tests/fixtures/dynalint/host_sync_ok.py": {"plan_step", "dispatch"},
}

# ---------------------------------------------------------------------------
# unbounded-await: network awaits with no deadline. An `await` of one of
# these calls is a point where a wedged peer can park a coroutine forever
# — the failure mode ISSUE 6's stall deadlines exist for. Bounded shapes
# pass: `await asyncio.wait_for(<call>, t)` (the call itself is not
# awaited) and any await lexically inside `async with asyncio.timeout(t)`.
# A deliberately unbounded await (server read loops idling between
# frames, engine-local queues whose producer is in-process) carries a
# `# dynalint: unbounded-ok` pragma on the line or the line above.
# ---------------------------------------------------------------------------

# Last-dotted-component call names that hit the network.
UNBOUNDED_AWAIT_FNS = {"open_connection", "read_frame"}

# `.get()` on a stream-queue receiver: the consumer side of a network-fed
# queue. Matched when the receiver's last dotted component (sans leading
# underscores) is one of these (`self._queue.get()`, `sub.queue.get()`,
# `seq.out.get()`); `msg.get(...)`/`dict.get(...)` receivers don't match.
UNBOUNDED_QUEUE_RECEIVERS = {"queue", "out"}

# Context managers that bound every await inside them.
TIMEOUT_SCOPES = {"asyncio.timeout", "asyncio.timeout_at", "async_timeout.timeout"}

# Wrappers that bound the coroutine they are handed.
TIMEOUT_WRAPPERS = {"asyncio.wait_for", "wait_for"}

# ---------------------------------------------------------------------------
# jax-pitfall: module roots whose use is flagged in __del__/signal handlers.
# ---------------------------------------------------------------------------

JAX_ROOTS = {"jax", "jnp"}

# Call names that register a signal handler (first arg: signum, second: fn).
SIGNAL_REGISTRARS = {"signal.signal", "loop.add_signal_handler"}

# Call/decorator names that enter a traced context.
JIT_WRAPPERS = {"jax.jit", "jit", "jax.pmap", "shard_map", "jax.shard_map"}

# ---------------------------------------------------------------------------
# unclosed-span: receivers whose `.span(...)` result must be closed.
# A dotted receiver matching one of these suffixes (tracer, self._tracer,
# disagg.tracer, ...) — or a direct `get_tracer(...).span(...)` chain — is
# treated as a dynamo_tpu.tracing Tracer. The span must be used as a
# context manager, or be bound to a name that is `.finish()`ed in the same
# scope; anything else leaks an open span (it never reaches the collector,
# and its phase silently vanishes from the waterfall).
# ---------------------------------------------------------------------------

TRACER_RECEIVER_SUFFIXES = ("tracer",)

# ---------------------------------------------------------------------------
# File selection.
# ---------------------------------------------------------------------------

# Directories skipped entirely (relative path fragments).
EXCLUDE_PARTS = {
    "__pycache__",
    ".git",
    # Lint fixtures intentionally contain violations.
    "tests/fixtures/dynalint",
    "tests/fixtures/dynacheck",
}
