"""dynalint core: AST visitors for the five detector classes.

Stdlib only (``ast`` + ``tokenize``). One pass per file; rule config and
the GUARDED_BY registry live in :mod:`tools.dynalint.config`.
"""

from __future__ import annotations

import ast
import io
import re
import tokenize
from dataclasses import dataclass, field
from pathlib import Path

from tools.dynalint import config as C

# ---------------------------------------------------------------------------
# Data model
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class Finding:
    path: str          # repo-relative posix path
    line: int
    col: int
    rule: str
    message: str

    def __str__(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: [{self.rule}] {self.message}"


@dataclass(frozen=True)
class Pragma:
    path: str
    line: int
    kind: str          # "allow" | "holds-lock" | "sync-ok" | "unbounded-ok"
    arg: str           # rule name for allow, lock name for holds-lock
    reason: str        # required for allow, empty otherwise
    # True when the comment has no code before it on its line: a
    # standalone pragma anchors to the statement BELOW it as well as any
    # statement spanning its line; a trailing pragma never anchors down.
    standalone: bool = True

    def __str__(self) -> str:
        detail = f"({self.reason})" if self.reason else ""
        tail = f"-{self.arg}" if self.arg else ""
        return f"{self.path}:{self.line}: {self.kind}{tail}{detail}"


_ALLOW_RE = re.compile(r"dynalint:\s*allow-([a-z][a-z0-9-]*)\s*\(\s*([^)]*?)\s*\)")
_HOLDS_RE = re.compile(r"dynalint:\s*holds-lock\s*\(\s*([A-Za-z_][A-Za-z0-9_]*)\s*\)")
# Intentional host-sync marker (blocking-host-sync rule): bare, no arg —
# prose may follow after the keyword (`# dynalint: sync-ok — reason`).
_SYNC_OK_RE = re.compile(r"dynalint:\s*sync-ok\b")
# Intentional deadline-free network await (unbounded-await rule): bare,
# no arg — prose may follow (`# dynalint: unbounded-ok — reason`).
_UNBOUNDED_OK_RE = re.compile(r"dynalint:\s*unbounded-ok\b")
# A pragma must START the comment (`# dynalint: ...`); "dynalint:"
# mid-comment is prose about the tool, not a directive.
_ANY_PRAGMA_RE = re.compile(r"^#+\s*dynalint:")


# ---------------------------------------------------------------------------
# Helpers
# ---------------------------------------------------------------------------


def dotted_name(node: ast.AST) -> str | None:
    """``a.b.c`` for Name/Attribute chains, else None."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _walk_excluding_defs(body: list[ast.stmt]):
    """Yield nodes in ``body`` without descending into nested function /
    class definitions (their code does not run in the enclosing scope)."""
    stack: list[ast.AST] = list(body)
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda, ast.ClassDef)):
            continue
        stack.extend(ast.iter_child_nodes(node))


def _jit_decorator(dec: ast.expr) -> bool:
    """True for ``@jax.jit`` / ``@jit`` / ``@partial(jax.jit, ...)``."""
    d = dotted_name(dec)
    if d in C.JIT_WRAPPERS:
        return True
    if isinstance(dec, ast.Call):
        f = dotted_name(dec.func)
        if f in C.JIT_WRAPPERS:
            return True
        if f in ("partial", "functools.partial") and dec.args:
            return dotted_name(dec.args[0]) in C.JIT_WRAPPERS
    return False


def _uses_jax(body: list[ast.stmt]) -> ast.AST | None:
    """First node in body rooted at jax/jnp (not descending into defs)."""
    for node in _walk_excluding_defs(body):
        if isinstance(node, (ast.Attribute, ast.Name)):
            d = dotted_name(node)
            if d and d.split(".")[0] in C.JAX_ROOTS:
                return node
    return None


def _stmt_span(node: ast.stmt) -> tuple[int, int]:
    """The line span a statement contributes to pragma anchoring.

    Simple statements span all their physical lines (the multi-line
    wrapped-call case). Compound statements (def/if/for/with/try/...)
    would otherwise span their whole BODY — a pragma deep inside a
    function must not blanket the function — so they contribute only
    their header region: ``lineno`` up to the line before the first
    body statement (a multi-line ``with a,\\n b:`` header, including
    its closing ``):`` line, is all header)."""
    end = getattr(node, "end_lineno", node.lineno) or node.lineno
    body = getattr(node, "body", None)
    if body and isinstance(body, list) and isinstance(body[0], ast.stmt):
        end = max(node.lineno, body[0].lineno - 1)
    return (node.lineno, end)


def statement_spans(tree: ast.Module) -> list[tuple[int, int]]:
    """Pragma-anchoring spans for every statement in ``tree``. Shared
    with dynacheck (tools/dynacheck/callgraph.py) so the two tiers can
    never disagree about which lines a pragma covers.

    ``except`` clauses are spanned too (header only, like any compound
    statement): they are ``ast.excepthandler``, not ``ast.stmt``, but a
    pragma directly above an ``except Exception:`` line is an
    established suppression form."""
    return [
        _stmt_span(node) for node in ast.walk(tree)
        if isinstance(node, (ast.stmt, ast.excepthandler))
    ]


def covered_lines(
    spans: list[tuple[int, int]], pragma_line: int, standalone: bool
) -> set[int]:
    """Lines a pragma at ``pragma_line`` suppresses: its own line plus
    every line of any span containing it, plus — ONLY for a STANDALONE
    pragma (a comment with no code before it on its line) — the span
    starting directly under it (the pragma-above-the-statement form).

    A TRAILING pragma (code before the comment) never anchors downward:
    a pragma on the last line of a multi-line statement, or on the
    closing ``):`` line of a multi-line header, covers that statement /
    header only and never bleeds onto the first body statement or the
    next sibling. Span membership alone cannot make this distinction —
    a closing-paren line belongs to no AST node — so the caller passes
    the tokenizer's verdict."""
    covered = {pragma_line}
    for lo, hi in spans:
        if lo <= pragma_line <= hi:
            covered.update(range(lo, hi + 1))
    if standalone:
        for lo, hi in spans:
            if lo == pragma_line + 1:
                covered.update(range(lo, hi + 1))
    return covered


# ---------------------------------------------------------------------------
# Per-file pass
# ---------------------------------------------------------------------------


class _FileLinter(ast.NodeVisitor):
    def __init__(self, path: str, tree: ast.Module, pragmas: list[Pragma]):
        self.path = path
        self.tree = tree
        self.findings: list[Finding] = []
        # Pragmas anchor to the FULL line span of the enclosing statement:
        # a `# dynalint: ...` on the opening line of a wrapped call must
        # suppress the finding even when the flagged node reports a later
        # lineno (and vice versa — a pragma on the argument line covers
        # the statement's opening line). Line-based matching alone missed
        # every multi-line statement.
        self._stmt_spans: list[tuple[int, int]] = statement_spans(tree)
        # Suppression lookup: (line, rule) from allow pragmas.
        self._allow: dict[int, set[str]] = {}
        # holds-lock pragma lines -> lock names.
        self._holds: dict[int, set[str]] = {}
        # sync-ok pragma lines (blocking-host-sync suppressions).
        self._sync_ok: set[int] = set()
        # unbounded-ok pragma lines (unbounded-await suppressions).
        self._unbounded_ok: set[int] = set()
        for p in pragmas:
            covered = covered_lines(self._stmt_spans, p.line, p.standalone)
            if p.kind == "allow":
                for ln in covered:
                    self._allow.setdefault(ln, set()).add(p.arg)
            elif p.kind == "sync-ok":
                self._sync_ok.update(covered)
            elif p.kind == "unbounded-ok":
                self._unbounded_ok.update(covered)
            else:
                self._holds.setdefault(p.line, set()).add(p.arg)

        # Context stacks.
        self._class_stack: list[str] = []
        self._func_stack: list[ast.AST] = []     # FunctionDef/AsyncFunctionDef/Lambda
        self._async_stack: list[bool] = []       # effective "on the event loop"
        self._held_locks: list[str] = []         # dotted lock exprs held lexically
        self._holds_pragma_stack: list[set[str]] = []
        self._global_decls: list[set[str]] = []  # per-function `global` names
        self._timeout_depth = 0                  # asyncio.timeout nesting

        # GUARDED_BY registry slice for this file.
        self._registry: dict[tuple[str | None, str], str] = {}
        for suffix, entries in C.GUARDED_BY.items():
            if path.endswith(suffix):
                self._registry.update(entries)

        # blocking-host-sync hot-path slice for this file.
        self._hot: set[str] = set()
        for suffix, funcs in C.HOT_STEP_FUNCS.items():
            if path.endswith(suffix):
                self._hot.update(funcs)

        # jax-pitfall bookkeeping (filled by _prescan).
        self._signal_handlers: set[str] = set()
        self._module_defs: dict[str, ast.AST] = {}
        self._jit_scanned: set[int] = set()      # id() of defs already scanned

    # -- reporting ---------------------------------------------------------

    def report(self, node: ast.AST, rule: str, message: str) -> None:
        line = getattr(node, "lineno", 0)
        # _covered_lines already expanded each pragma over its statement
        # span AND the pragma-above-the-statement line; probing line-1
        # here would bleed a pragma'd statement's coverage onto its
        # NEXT sibling.
        if rule in self._allow.get(line, ()):  # suppressed by pragma
            return
        self.findings.append(
            Finding(self.path, line, getattr(node, "col_offset", 0), rule, message)
        )

    # -- entry -------------------------------------------------------------

    def run(self) -> list[Finding]:
        self._prescan()
        self.visit(self.tree)
        self._check_unclosed_spans()
        return self.findings

    def _prescan(self) -> None:
        """Collect module-level defs and signal-handler registrations."""
        for node in ast.walk(self.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._module_defs.setdefault(node.name, node)
            elif isinstance(node, ast.Call):
                f = dotted_name(node.func)
                is_registrar = f in C.SIGNAL_REGISTRARS or (
                    isinstance(node.func, ast.Attribute)
                    and node.func.attr == "add_signal_handler"
                )
                if is_registrar:
                    for arg in node.args[1:]:
                        if isinstance(arg, ast.Name):
                            self._signal_handlers.add(arg.id)

    # -- scope tracking ----------------------------------------------------

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        self._class_stack.append(node.name)
        self.generic_visit(node)
        self._class_stack.pop()

    def _enter_function(self, node, is_async: bool) -> None:
        holds = set()
        for probe in (node.lineno, node.lineno - 1):
            holds |= self._holds.get(probe, set())
        # Decorator lines shift lineno; also probe the first decorator line.
        if getattr(node, "decorator_list", None):
            dline = node.decorator_list[0].lineno
            holds |= self._holds.get(dline - 1, set())
        globals_declared: set[str] = set()
        body = node.body if isinstance(node.body, list) else [node.body]
        for sub in _walk_excluding_defs(body):
            if isinstance(sub, ast.Global):
                globals_declared.update(sub.names)
        self._func_stack.append(node)
        self._async_stack.append(is_async)
        self._holds_pragma_stack.append(holds)
        self._global_decls.append(globals_declared)

    def _exit_function(self) -> None:
        self._func_stack.pop()
        self._async_stack.pop()
        self._holds_pragma_stack.pop()
        self._global_decls.pop()

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self._check_jax_def(node, is_async=False)
        self._enter_function(node, is_async=False)
        self.generic_visit(node)
        self._exit_function()

    def visit_AsyncFunctionDef(self, node: ast.AsyncFunctionDef) -> None:
        self._check_jax_def(node, is_async=True)
        self._enter_function(node, is_async=True)
        self.generic_visit(node)
        self._exit_function()

    def visit_Lambda(self, node: ast.Lambda) -> None:
        self._enter_function(node, is_async=False)
        self.generic_visit(node)
        self._exit_function()

    def _in_async(self) -> bool:
        return bool(self._async_stack) and self._async_stack[-1]

    def _current_func_name(self) -> str | None:
        for f in reversed(self._func_stack):
            if isinstance(f, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return f.name
        return None

    # -- with-lock tracking ------------------------------------------------

    def _visit_with(self, node) -> None:
        added = 0
        timeouts = 0
        for item in node.items:
            d = dotted_name(item.context_expr)
            if d is not None:
                self._held_locks.append(d)
                added += 1
            # `async with asyncio.timeout(t):` bounds every await inside.
            if isinstance(item.context_expr, ast.Call):
                cd = dotted_name(item.context_expr.func)
                if cd in C.TIMEOUT_SCOPES:
                    timeouts += 1
        self._timeout_depth += timeouts
        self.generic_visit(node)
        self._timeout_depth -= timeouts
        if added:
            del self._held_locks[len(self._held_locks) - added:]

    def visit_With(self, node: ast.With) -> None:
        self._visit_with(node)

    def visit_AsyncWith(self, node: ast.AsyncWith) -> None:
        self._visit_with(node)

    # -- rule 1: fire-and-forget tasks ------------------------------------

    def visit_Expr(self, node: ast.Expr) -> None:
        call = node.value
        if isinstance(call, ast.Call) and self._is_task_spawn(call):
            self.report(
                node, C.RULE_FIRE_AND_FORGET,
                "task result is discarded: exceptions are lost and the task "
                "can be garbage-collected mid-flight; store it, await it, or "
                "attach a done-callback",
            )
        self.generic_visit(node)

    @staticmethod
    def _is_task_spawn(call: ast.Call) -> bool:
        func = call.func
        if isinstance(func, ast.Name):
            # `from asyncio import create_task/ensure_future` call sites.
            return func.id in ("create_task", "ensure_future")
        if isinstance(func, ast.Attribute) and func.attr in ("create_task", "ensure_future"):
            root = dotted_name(func.value)
            # asyncio.create_task / loop.create_task / get_event_loop().
            # TaskGroup.create_task holds its own reference — not matched
            # (receivers named tg/group by convention).
            if root is None:
                return isinstance(func.value, ast.Call)  # get_event_loop().create_task
            return root == "asyncio" or root.endswith("loop")
        return False

    # -- rule 2 dispatch + rule 5(c) on calls ------------------------------

    def visit_Call(self, node: ast.Call) -> None:
        if self._in_async():
            self._check_blocking(node)
        self._check_jit_call(node)
        self._check_mutator_call(node)
        self._check_host_sync(node)
        self.generic_visit(node)

    # -- rule 7: blocking host syncs in step-loop hot paths ----------------

    def _check_host_sync(self, node: ast.Call) -> None:
        """Flag device->host synchronization calls inside registered
        step-loop hot paths (the plan/dispatch side of the async engine):
        np.asarray / fetch_replicated / .item() / .block_until_ready()
        there serialize host work with device compute. Nested named defs
        (the commit closures) are their own scope — _current_func_name
        resolves to the innermost named def, which is not in the hot set
        — so commit-side landings sync freely. Suppressed by a
        `# dynalint: sync-ok` pragma on the line or the line above."""
        if not self._hot:
            return
        fname = self._current_func_name()
        if fname is None or fname not in self._hot:
            return
        func = node.func
        what = None
        if isinstance(func, ast.Attribute):
            if func.attr in C.HOST_SYNC_METHODS:
                what = f".{func.attr}()"
            elif func.attr == "asarray" and dotted_name(func.value) in C.HOST_SYNC_ASARRAY_ROOTS:
                what = "np.asarray()"
            elif func.attr in C.HOST_SYNC_FNS:
                what = f"{func.attr}()"
        elif isinstance(func, ast.Name) and func.id in C.HOST_SYNC_FNS:
            what = f"{func.id}()"
        if what is None:
            return
        line = node.lineno
        if line in self._sync_ok:  # span-expanded; see _covered_lines
            return
        self.report(
            node, C.RULE_HOST_SYNC,
            f"{what} inside step-loop hot path {fname!r} blocks the host "
            "on device state, serializing scheduling with device compute; "
            "move the landing to the commit side, or mark an intentional "
            "sync with `# dynalint: sync-ok`",
        )

    # -- rule 8: unbounded network awaits ----------------------------------

    def visit_Await(self, node: ast.Await) -> None:
        self._check_unbounded_await(node)
        self.generic_visit(node)

    def _check_unbounded_await(self, node: ast.Await) -> None:
        """``await <network call>`` with no deadline is a point where a
        wedged peer parks this coroutine forever (the stalled-but-
        connected failure mode migration can never see). Bounded shapes
        pass: ``asyncio.wait_for(...)`` (the inner call is an argument,
        not awaited) and any await inside ``async with asyncio.timeout``.
        Deliberate unbounded awaits carry `# dynalint: unbounded-ok`."""
        call = node.value
        if not isinstance(call, ast.Call):
            return
        d = dotted_name(call.func)
        if d in C.TIMEOUT_WRAPPERS:
            return
        last = d.rsplit(".", 1)[-1] if d else None
        what = None
        if last in C.UNBOUNDED_AWAIT_FNS:
            what = f"{last}()"
        elif last == "get" and isinstance(call.func, ast.Attribute):
            recv = dotted_name(call.func.value)
            recv_last = recv.rsplit(".", 1)[-1].lstrip("_") if recv else ""
            if recv_last in C.UNBOUNDED_QUEUE_RECEIVERS:
                what = f"{recv}.get()"
        if what is None:
            return
        if self._timeout_depth > 0:
            return
        line = node.lineno
        if line in self._unbounded_ok:  # span-expanded; see _covered_lines
            return
        self.report(
            node, C.RULE_UNBOUNDED_AWAIT,
            f"await {what} has no deadline: a wedged peer parks this "
            "coroutine forever; wrap it in asyncio.wait_for / an "
            "asyncio.timeout scope, or mark a deliberately unbounded "
            "await with `# dynalint: unbounded-ok`",
        )

    def _check_blocking(self, node: ast.Call) -> None:
        d = dotted_name(node.func)
        if d is None:
            return
        if d == "open":
            self.report(
                node, C.RULE_BLOCKING_IN_ASYNC,
                "sync file I/O (open) inside async def blocks the event "
                "loop; use asyncio.to_thread",
            )
            return
        if d in C.BLOCKING_CALLS:
            self.report(node, C.RULE_BLOCKING_IN_ASYNC, C.BLOCKING_CALLS[d])
            return
        root = d.split(".")[0]
        if root in C.BLOCKING_ROOTS:
            self.report(node, C.RULE_BLOCKING_IN_ASYNC, C.BLOCKING_ROOTS[root])

    # -- rule 3: broad except ---------------------------------------------

    def visit_ExceptHandler(self, node: ast.ExceptHandler) -> None:
        if self._is_broad(node.type) and not self._handler_is_hygienic(node):
            what = "bare except" if node.type is None else "except Exception"
            self.report(
                node, C.RULE_BROAD_EXCEPT,
                f"{what} that neither logs, re-raises, nor carries a "
                "`# dynalint: allow-broad-except(<reason>)` pragma silently "
                "swallows real failures",
            )
        self.generic_visit(node)

    @staticmethod
    def _is_broad(type_node: ast.expr | None) -> bool:
        if type_node is None:
            return True
        names = type_node.elts if isinstance(type_node, ast.Tuple) else [type_node]
        return any(
            isinstance(n, ast.Name) and n.id in ("Exception", "BaseException")
            for n in names
        )

    @staticmethod
    def _handler_is_hygienic(node: ast.ExceptHandler) -> bool:
        log_attrs = {
            "debug", "info", "warning", "error", "exception", "critical", "log",
        }
        for sub in _walk_excluding_defs(node.body):
            if isinstance(sub, ast.Raise):
                return True
            if isinstance(sub, ast.Call):
                d = dotted_name(sub.func)
                if d in ("traceback.print_exc", "warnings.warn"):
                    return True
                if isinstance(sub.func, ast.Attribute) and sub.func.attr in log_attrs:
                    # Only count it as logging when the receiver looks like
                    # a logger (log/logger/_log/self.log/lg...) — otherwise
                    # math.log(x) or stats.update(...) would legitimize a
                    # swallowing handler.
                    recv = dotted_name(sub.func.value)
                    last = recv.split(".")[-1] if recv else ""
                    if "log" in last.lower() or last == "lg":
                        return True
            # `except Exception as e:` where the body references `e` is
            # surfacing the error somewhere (str(e) into a reply, a status
            # line, ...), not swallowing it.
            if (
                node.name
                and isinstance(sub, ast.Name)
                and sub.id == node.name
                and isinstance(sub.ctx, ast.Load)
            ):
                return True
        return False

    # -- rule 4: lock discipline ------------------------------------------

    def visit_Assign(self, node: ast.Assign) -> None:
        for target in node.targets:
            self._check_mutation_target(target, node)
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        self._check_mutation_target(node.target, node)
        self.generic_visit(node)

    def visit_Delete(self, node: ast.Delete) -> None:
        for target in node.targets:
            self._check_mutation_target(target, node)
        self.generic_visit(node)

    def _check_mutator_call(self, node: ast.Call) -> None:
        func = node.func
        if isinstance(func, ast.Attribute) and func.attr in C.MUTATOR_METHODS:
            self._check_mutation_target(func.value, node)

    def _base_attr(self, target: ast.expr) -> tuple[str | None, str] | None:
        """Registry key for the object a mutation lands on.

        ``self.X...`` -> (class, X); bare module global ``G...`` -> (None, G).
        Peels subscripts: ``self.X[k] = v`` mutates X.
        """
        while isinstance(target, ast.Subscript):
            target = target.value
        if (
            isinstance(target, ast.Attribute)
            and isinstance(target.value, ast.Name)
            and target.value.id == "self"
            and self._class_stack
        ):
            return (self._class_stack[-1], target.attr)
        if isinstance(target, ast.Name):
            if not self._func_stack:
                return (None, target.id)  # module top level (exempted later)
            # A Store/Del on a bare name inside a function hits the module
            # global only under a `global` declaration — without one it's a
            # local, including locals that shadow a registered name.
            if self._global_decls and target.id in self._global_decls[-1]:
                return (None, target.id)
            # Load context (mutator method call, e.g. `_free.append(x)`):
            # no `global` needed to mutate through the name.
            if isinstance(target.ctx, ast.Load):
                return (None, target.id)
        return None

    def _check_mutation_target(self, target: ast.expr, site: ast.AST) -> None:
        if not self._registry:
            return
        key = self._base_attr(target)
        if key is None or key not in self._registry:
            return
        lock = self._registry[key]
        if lock == C.EXTERNAL:
            return
        scope, attr = key
        fname = self._current_func_name()
        if fname is None:
            return  # module top level: initial binding
        if scope is not None and fname == "__init__":
            return  # construction precedes sharing
        want = f"self.{lock}" if scope is not None else lock
        if want in self._held_locks:
            return
        if self._holds_pragma_stack and lock in self._holds_pragma_stack[-1]:
            return
        owner = f"{scope}.{attr}" if scope else attr
        self.report(
            site, C.RULE_LOCK_DISCIPLINE,
            f"{owner} is registered GUARDED_BY({lock}) but is mutated "
            f"outside `with {want}` (add the lock, or annotate the enclosing "
            f"def with `# dynalint: holds-lock({lock})` if the caller holds it)",
        )

    # -- rule 5: jax pitfalls ---------------------------------------------

    def _check_jax_def(self, node, is_async: bool) -> None:
        # (a) jax/jnp inside __del__ or a registered signal handler.
        hazard = None
        if node.name == "__del__":
            hazard = "__del__ runs at gc time, possibly during interpreter teardown"
        elif node.name in self._signal_handlers:
            hazard = "signal handlers run reentrantly at arbitrary points"
        if hazard:
            use = _uses_jax(node.body)
            if use is not None:
                self.report(
                    use, C.RULE_JAX_PITFALL,
                    f"jax/jnp call inside {node.name}: {hazard}; dispatching "
                    "device work here can deadlock or crash the runtime",
                )
        # (b) @jax.jit over a function that touches bound mutable state.
        for dec in node.decorator_list:
            if _jit_decorator(dec):
                args = node.args.posonlyargs + node.args.args
                is_method = bool(args) and args[0].arg == "self" and self._class_stack
                refs_self = any(
                    isinstance(n, ast.Name) and n.id == "self"
                    for n in ast.walk(node)
                )
                if is_method or refs_self:
                    self.report(
                        dec, C.RULE_JAX_PITFALL,
                        f"@jit on {node.name!r} captures `self`: bound mutable "
                        "state is baked in at trace time (stale closures, "
                        "silent retraces); jit a pure function of arrays instead",
                    )
                self._scan_traced_body(node)

    def _check_jit_call(self, node: ast.Call) -> None:
        # (c) side effects in functions handed to jax.jit(f)/shard_map(f).
        if dotted_name(node.func) not in C.JIT_WRAPPERS or not node.args:
            return
        target = node.args[0]
        # jax.jit(partial(f, ...)) — unwrap to f.
        if isinstance(target, ast.Call) and dotted_name(target.func) in (
            "partial", "functools.partial",
        ) and target.args:
            target = target.args[0]
        fn = None
        if isinstance(target, ast.Name):
            fn = self._module_defs.get(target.id)
        elif isinstance(target, ast.Lambda):
            fn = target
        if fn is not None:
            self._scan_traced_body(fn)

    def _scan_traced_body(self, fn) -> None:
        if id(fn) in self._jit_scanned:
            return
        self._jit_scanned.add(id(fn))
        body = fn.body if isinstance(fn.body, list) else [fn.body]
        for sub in ast.walk(ast.Module(body=list(body), type_ignores=[])):
            if isinstance(sub, ast.Call):
                d = dotted_name(sub.func)
                if d == "print":
                    self.report(
                        sub, C.RULE_JAX_PITFALL,
                        "print() inside a jitted/shard_mapped function runs "
                        "only at trace time (and re-runs on every retrace); "
                        "use jax.debug.print",
                    )
            elif isinstance(sub, (ast.Global, ast.Nonlocal)):
                self.report(
                    sub, C.RULE_JAX_PITFALL,
                    "global/nonlocal mutation inside a traced function is a "
                    "trace-time side effect: it will not re-run per call",
                )
            elif isinstance(sub, (ast.Assign, ast.AugAssign)):
                targets = sub.targets if isinstance(sub, ast.Assign) else [sub.target]
                for t in targets:
                    while isinstance(t, ast.Subscript):
                        t = t.value
                    if (
                        isinstance(t, ast.Attribute)
                        and isinstance(t.value, ast.Name)
                        and t.value.id == "self"
                    ):
                        self.report(
                            sub, C.RULE_JAX_PITFALL,
                            f"mutation of self.{t.attr} inside a traced "
                            "function happens at trace time only — the jitted "
                            "executable will never update it again",
                        )

    # -- rule 6: unclosed spans -------------------------------------------

    def _check_unclosed_spans(self) -> None:
        """A ``tracer.span(...)`` must be used as a context manager, or be
        bound to a name that is ``.finish()``ed in the same scope. An open
        span never reaches the collector — its phase silently vanishes
        from every waterfall."""
        parents: dict[ast.AST, ast.AST] = {}
        for node in ast.walk(self.tree):
            for child in ast.iter_child_nodes(node):
                parents[child] = node
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Call) and self._is_span_call(node):
                self._classify_span_use(node, parents)

    @staticmethod
    def _is_span_call(call: ast.Call) -> bool:
        func = call.func
        if not (isinstance(func, ast.Attribute) and func.attr == "span"):
            return False
        d = dotted_name(func.value)
        if d is not None:
            return d.lower().endswith(C.TRACER_RECEIVER_SUFFIXES)
        # Direct chain: get_tracer("svc").span(...)
        if isinstance(func.value, ast.Call):
            g = dotted_name(func.value.func)
            return g is not None and g.rsplit(".", 1)[-1] == "get_tracer"
        return False

    def _classify_span_use(
        self, call: ast.Call, parents: dict[ast.AST, ast.AST]
    ) -> None:
        parent = parents.get(call)
        # `with tracer.span(...) as s:` — the blessed form.
        if isinstance(parent, ast.withitem) and parent.context_expr is call:
            return
        # `s = tracer.span(...)` escapes the with-shape only if `s.finish()`
        # is called somewhere in the same scope (e.g. a root span closed in
        # a `finally`).
        if (
            isinstance(parent, ast.Assign)
            and parent.value is call
            and len(parent.targets) == 1
            and isinstance(parent.targets[0], ast.Name)
        ):
            name = parent.targets[0].id
            scope: ast.AST | None = parent
            while scope is not None and not isinstance(
                scope, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda, ast.Module)
            ):
                scope = parents.get(scope)
            for sub in ast.walk(scope or self.tree):
                if (
                    isinstance(sub, ast.Call)
                    and isinstance(sub.func, ast.Attribute)
                    and sub.func.attr == "finish"
                    and isinstance(sub.func.value, ast.Name)
                    and sub.func.value.id == name
                ):
                    return
            self.report(
                call, C.RULE_UNCLOSED_SPAN,
                f"span bound to {name!r} is never finished: use "
                "`with tracer.span(...) as ...:` or call "
                f"`{name}.finish()` on every exit path",
            )
            return
        self.report(
            call, C.RULE_UNCLOSED_SPAN,
            "span result is not used as a context manager (and not bound "
            "to a finished name): the span never reaches the collector",
        )


# ---------------------------------------------------------------------------
# Pragma extraction
# ---------------------------------------------------------------------------


def comment_tokens(source: str) -> list[tuple[int, str, bool]]:
    """(line, text, standalone) for every comment — ``standalone`` means
    nothing but whitespace precedes the comment on its line. Shared with
    dynacheck so both tiers classify pragmas identically."""
    try:
        tokens = tokenize.generate_tokens(io.StringIO(source).readline)
    except (tokenize.TokenError, IndentationError, SyntaxError):
        return []
    lines = source.splitlines()
    out: list[tuple[int, str, bool]] = []
    try:
        for t in tokens:
            if t.type != tokenize.COMMENT:
                continue
            row, col = t.start
            before = lines[row - 1][:col] if row - 1 < len(lines) else ""
            out.append((row, t.string, not before.strip()))
    except (tokenize.TokenError, IndentationError, SyntaxError):
        pass
    return out


def extract_pragmas(path: str, source: str) -> tuple[list[Pragma], list[Finding]]:
    pragmas: list[Pragma] = []
    errors: list[Finding] = []
    for line, text, standalone in comment_tokens(source):
        if not _ANY_PRAGMA_RE.search(text):
            continue
        matched = False
        for m in _ALLOW_RE.finditer(text):
            rule, reason = m.group(1), m.group(2).strip()
            matched = True
            if rule not in C.ALL_RULES:
                errors.append(Finding(
                    path, line, 0, "malformed-pragma",
                    f"allow pragma names unknown rule {rule!r} "
                    f"(known: {', '.join(C.ALL_RULES)})",
                ))
            elif not reason:
                errors.append(Finding(
                    path, line, 0, "malformed-pragma",
                    f"allow-{rule} pragma requires a non-empty reason",
                ))
            else:
                pragmas.append(Pragma(path, line, "allow", rule, reason, standalone))
        for m in _HOLDS_RE.finditer(text):
            matched = True
            pragmas.append(Pragma(path, line, "holds-lock", m.group(1), "", standalone))
        if _SYNC_OK_RE.search(text):
            matched = True
            pragmas.append(Pragma(path, line, "sync-ok", "", "", standalone))
        if _UNBOUNDED_OK_RE.search(text):
            matched = True
            pragmas.append(Pragma(path, line, "unbounded-ok", "", "", standalone))
        if not matched:
            errors.append(Finding(
                path, line, 0, "malformed-pragma",
                "unparseable dynalint pragma; expected "
                "`dynalint: allow-<rule>(<reason>)` or "
                "`dynalint: holds-lock(<lock>)`",
            ))
    return pragmas, errors


# ---------------------------------------------------------------------------
# Driver
# ---------------------------------------------------------------------------


@dataclass
class LintResult:
    findings: list[Finding] = field(default_factory=list)
    pragmas: list[Pragma] = field(default_factory=list)


def lint_file(path: Path, repo_root: Path | None = None) -> LintResult:
    rel = path.resolve()
    if repo_root is not None:
        try:
            rel = rel.relative_to(repo_root.resolve())
        except ValueError:
            pass
    rel_str = rel.as_posix()
    source = path.read_text(encoding="utf-8", errors="replace")
    pragmas, errors = extract_pragmas(rel_str, source)
    result = LintResult(findings=list(errors), pragmas=pragmas)
    try:
        tree = ast.parse(source, filename=rel_str)
    except SyntaxError as e:
        result.findings.append(
            Finding(rel_str, e.lineno or 0, e.offset or 0, "syntax-error", e.msg or "syntax error")
        )
        return result
    result.findings.extend(_FileLinter(rel_str, tree, pragmas).run())
    result.findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return result


def _excluded(rel: str) -> bool:
    return any(part in rel for part in C.EXCLUDE_PARTS)


def iter_py_files(paths: list[Path], repo_root: Path) -> list[Path]:
    out: list[Path] = []
    for p in paths:
        if p.is_file() and p.suffix == ".py":
            out.append(p)
        elif p.is_dir():
            for f in sorted(p.rglob("*.py")):
                try:
                    rel = f.resolve().relative_to(repo_root.resolve()).as_posix()
                except ValueError:
                    rel = f.as_posix()
                if not _excluded(rel):
                    out.append(f)
    return out


def lint_paths(paths: list[Path], repo_root: Path | None = None) -> LintResult:
    root = repo_root or Path.cwd()
    total = LintResult()
    for f in iter_py_files(paths, root):
        r = lint_file(f, root)
        total.findings.extend(r.findings)
        total.pragmas.extend(r.pragmas)
    total.findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return total
