"""Fleet autoscaling smoke (ISSUE 14): 3 mocker workers on the fleet
harness's virtual clock with the closed-loop planner ON, hit by a burst
that forces one reactive scale-up and, once it passes, one drained
scale-down.

Asserts the user-visible contract:

- the burst actuates ``scale_up`` and the quiet tail actuates
  ``scale_down`` through the connector, and the scaled-down worker
  retires via GRACEFUL DRAIN (finishes everything it accepted — never a
  kill);
- every client stream is byte-identical to an equal-workload run with a
  frozen pool (autoscaling moves capacity, never tokens), with zero
  broken streams and zero sheds;
- the planner's decision counters and replica gauges populate on a real
  MetricsRegistry through the PR 13 aggregator export path
  (``planner_decisions_total{action=...}``, ``planner_current_replicas``
  / ``planner_target_replicas`` per pool, ``planner_cycles_total``) and
  the ``/fleet`` payload carries the controller's actions and reasons.

CI usage (`.github/workflows/ci.yml` fleet-smoke step) and local:

    python tools/fleet_smoke.py
"""

from __future__ import annotations

import sys
from pathlib import Path

# Runnable straight from a checkout (CI also pip-installs the package).
sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def main() -> int:
    from dynamo_tpu.fleet.harness import FleetHarness, FleetSpec
    from dynamo_tpu.fleet.workload import TenantSpec

    # Quiet base load a 3-worker pool holds easily, then one hard burst
    # window (4x) that a frozen pool could also absorb — the point here
    # is the ACTUATION, not an SLO gap (bench run_fleet_ab proves that).
    tenants = [
        TenantSpec(
            name="smoke", users=2_000, rps=8.0,
            burst_rps=32.0, burst_every_s=60.0, burst_len_s=12.0,
            isl=32, osl=8, shared_prefix_tokens=16,
        ),
    ]

    def spec(planner_on: bool) -> FleetSpec:
        return FleetSpec(
            tenants=tenants, duration_s=55.0, seed=11,
            planner_on=planner_on, static_replicas=3, initial_replicas=3,
            min_replicas=2, max_replicas=8, keep_streams=True,
        )

    # Frozen-pool twin first: the byte-identity reference.
    static = FleetHarness(spec(False)).run()
    h = FleetHarness(spec(True))
    report = h.run()

    assert report.scale_ups >= 1, (
        f"burst never actuated a scale-up: {report.decisions}"
    )
    assert report.scale_downs >= 1, (
        f"quiet tail never actuated a scale-down: {report.decisions}"
    )
    assert report.drained_retired >= 1, (
        "scale-down did not retire a worker via graceful drain"
    )
    assert report.peak_replicas > 3, report.peak_replicas
    assert report.broken_streams == 0 and report.shed == 0, (
        report.broken_streams, report.shed,
    )
    assert report.completed == report.requests == static.requests
    assert report.streams == static.streams, (
        "autoscaling changed client-visible bytes"
    )

    # Planner observability through the PR 13 aggregator export path.
    import asyncio

    from dynamo_tpu.obs.aggregator import FleetAggregator
    from dynamo_tpu.runtime.metrics import MetricsRegistry

    async def export() -> tuple[str, dict]:
        agg = FleetAggregator(store=None)
        agg.attach_controller(h.controller)
        registry = MetricsRegistry()
        before = []
        agg.bind(registry, before)
        for cb in before:
            cb()
        return registry.render().decode(), agg.fleet_payload()

    text, payload = asyncio.new_event_loop().run_until_complete(export())
    for needle in (
        'planner_decisions_total{action="scale_up"',
        'planner_decisions_total{action="scale_down"',
        "planner_cycles_total",
        'planner_current_replicas{component="backend"',
        'planner_target_replicas{component="backend"',
    ):
        assert needle in text, f"missing planner series: {needle}\n{text}"

    planner_section = payload["planner"]
    assert planner_section is not None
    assert planner_section["cycles"] == h.controller.cycles > 0
    assert planner_section["decisions"]["scale_up"] >= 1
    assert planner_section["pools"]["backend"]["last_action"]
    assert planner_section["last_plan"] is not None

    print(
        "fleet smoke OK: "
        f"{report.requests} requests, peak {report.peak_replicas} workers, "
        f"{report.scale_ups} scale-up(s), {report.scale_downs} "
        f"scale-down(s), {report.drained_retired} drained, "
        f"0 broken streams, streams byte-identical to the frozen pool, "
        f"planner gauges + /fleet section populated"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
