"""Quantized-KV smoke: a mocker-backed frontend deployed with
``--kv-dtype int8`` serves a streaming request end to end, and the
worker's /metrics reports the int8 layout — ``kv_cache_dtype_int8 1``,
the labeled ``kv_cache_dtype{kv_dtype="int8"}`` info gauge, and a
bytes-per-block strictly under the bf16 page.

This is the user-visible contract of the quantized KV cache (ISSUE 8):
flipping the storage dtype is a deployment knob whose capacity effect is
OBSERVABLE on the metrics surface, and never changes which tokens a
request streams (the mocker twin at bf16 must match byte for byte; the
real engine's quality guard and byte-stability invariants are pinned by
tests/test_kv_quant.py).

CI usage (`.github/workflows/ci.yml` kvquant-smoke step) and local:

    python tools/kvquant_smoke.py
"""

from __future__ import annotations

import asyncio
import sys
from pathlib import Path

# Runnable straight from a checkout (CI also pip-installs the package).
sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from tools.megastep_smoke import stream_text  # noqa: E402


async def run_one(kv_dtype: str) -> tuple[str, str]:
    """Boot store + mocker (kv_dtype) + frontend with a live status
    server, stream one greedy request, and return (streamed text, the
    worker's /metrics text)."""
    import aiohttp

    from dynamo_tpu.backends.mocker import run_mocker
    from dynamo_tpu.frontend.main import run_frontend
    from dynamo_tpu.llm.mocker import MockEngineArgs
    from dynamo_tpu.runtime import DistributedRuntime
    from dynamo_tpu.runtime.status_server import SystemStatusServer
    from dynamo_tpu.runtime.store import StoreServer

    store = StoreServer()
    await store.start()
    worker_rt = await DistributedRuntime.create(store.address)
    status = SystemStatusServer(host="127.0.0.1", port=0)
    await status.start()
    worker_rt.status = status  # bind_kv_cache_gauges hooks in run_mocker
    served = asyncio.Event()
    worker = asyncio.create_task(
        run_mocker(
            worker_rt,
            model_name="mock",
            engine_args=MockEngineArgs(
                num_kv_blocks=4096,
                block_size=8,
                speedup_ratio=50.0,
                kv_dtype=kv_dtype,
                kv_read_us_per_block=5.0,
            ),
            served_event=served,
        )
    )
    await asyncio.wait_for(served.wait(), 30)
    front_rt = await DistributedRuntime.create(store.address)
    ready = asyncio.Event()
    services: list = []
    frontend = asyncio.create_task(
        run_frontend(
            front_rt, http_host="127.0.0.1", http_port=0,
            router_mode="kv", ready_event=ready, service_out=services,
        )
    )
    await asyncio.wait_for(ready.wait(), 30)
    base = f"http://127.0.0.1:{services[0].port}"

    async with aiohttp.ClientSession() as s:
        for _ in range(200):
            async with s.get(f"{base}/v1/models") as r:
                if (await r.json())["data"]:
                    break
            await asyncio.sleep(0.05)
        else:
            raise TimeoutError("model never appeared on frontend")

        text = await stream_text(
            s, f"{base}/v1/chat/completions",
            {
                "model": "mock",
                "messages": [{"role": "user", "content": "kv quant smoke"}],
                "max_tokens": 32,
                "temperature": 0,
                "stream": True,
            },
        )
        async with s.get(
            f"http://127.0.0.1:{status.port}/metrics"
        ) as r:
            assert r.status == 200
            metrics = await r.text()

    for task in (worker, frontend):
        task.cancel()
    for rt in (worker_rt, front_rt):
        await rt.shutdown()
    await status.stop()
    await store.stop()
    return text, metrics


def _gauge_value(metrics: str, name: str, must_contain: str = "") -> float:
    for line in metrics.splitlines():
        if line.startswith(name) and must_contain in line:
            return float(line.rsplit(None, 1)[-1])
    raise AssertionError(f"gauge {name!r} ({must_contain!r}) not on /metrics")


async def run() -> None:
    text_i8, m_i8 = await run_one("int8")
    assert text_i8, "int8 deployment streamed nothing"
    assert _gauge_value(m_i8, "dynamo_kv_cache_dtype_int8") == 1.0
    assert _gauge_value(m_i8, "dynamo_kv_cache_dtype", 'kv_dtype="int8"') == 1.0
    bytes_i8 = _gauge_value(m_i8, "dynamo_kv_cache_bytes_per_block")
    cap_i8 = _gauge_value(m_i8, "dynamo_kv_cache_capacity_blocks")
    assert cap_i8 > 0

    text_bf, m_bf = await run_one("bf16")
    assert _gauge_value(m_bf, "dynamo_kv_cache_dtype_int8") == 0.0
    bytes_bf = _gauge_value(m_bf, "dynamo_kv_cache_bytes_per_block")
    assert bytes_i8 < bytes_bf, (
        f"int8 bytes/block {bytes_i8} not under bf16 {bytes_bf}"
    )
    assert text_i8 == text_bf, (
        f"kv_dtype changed the stream:\n  int8: {text_i8!r}\n"
        f"  bf16: {text_bf!r}"
    )
    print(
        f"kvquant-smoke OK: {len(text_i8)} chars bit-identical int8 vs "
        f"bf16; /metrics reports int8 at {bytes_i8:.0f} B/block vs bf16 "
        f"{bytes_bf:.0f} ({bytes_i8 / bytes_bf:.3f}x)", flush=True,
    )


def main() -> int:
    asyncio.run(run())
    return 0


if __name__ == "__main__":
    sys.exit(main())
