"""Megastep smoke: a mocker-backed frontend with ``--megastep-k 8``
streams BIT-IDENTICAL output to a twin deployment running single-step
(k=1), and the k=8 worker records ``engine_megastep`` stat spans (the
per-dispatch fusion evidence) that the k=1 worker must not.

Two phases:

1. DECODE-ONLY (ISSUE 7): one greedy request against a plain decode
   deployment — the original megastep contract.
2. MIXED TRAFFIC (ISSUE 12): chunked scheduling + spec decode, a short
   request decoding WHILE a long prompt chunks through the scheduler —
   the universal-megastep contract. Both streams must match the k=1
   twin byte for byte, the worker must record >= 1 FUSED mixed dispatch
   (prefill chunks / verify rows riding the scanned body, the
   ``fused_mixed_dispatches`` gauge), and ZERO batches may fall back to
   forced k=1 (``megastep_forced_single`` — only a stop watch wider
   than the device's 8 slots may ever trip it, and no request here
   carries one).

This is the user-visible contract of device-side multi-step decode:
fusing k iterations into one device dispatch changes HOW OFTEN the host
and device talk — one fixed dispatch overhead per k tokens instead of
per token — never which tokens are emitted.

CI usage (`.github/workflows/ci.yml` megastep-smoke step) and local:

    python tools/megastep_smoke.py
"""

from __future__ import annotations

import asyncio
import sys
from pathlib import Path

# Runnable straight from a checkout (CI also pip-installs the package).
sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


async def stream_text(session, url: str, body: dict) -> str:
    """POST a streaming chat completion; return the concatenated content."""
    import json

    parts: list[str] = []
    async with session.post(url, json=body) as resp:
        assert resp.status == 200, await resp.text()
        async for raw in resp.content:
            line = raw.decode("utf-8", "replace").strip()
            if not line.startswith("data:") or "[DONE]" in line:
                continue
            chunk = json.loads(line[len("data:"):])
            for choice in chunk.get("choices", []):
                parts.append((choice.get("delta") or {}).get("content") or "")
    return "".join(parts)


def _chat_body(content: str, max_tokens: int) -> dict:
    return {
        "model": "mock",
        "messages": [{"role": "user", "content": content}],
        "max_tokens": max_tokens,
        "temperature": 0,
        "stream": True,
    }


async def run_one(megastep_k: int, mixed: bool) -> tuple[list[str], dict]:
    """Boot store + mocker (megastep k) + frontend and stream the phase's
    request(s); return (streamed texts, worker scheduler gauges).

    ``mixed`` drives the ISSUE 12 traffic shape: chunked scheduling +
    spec decode, with a LONG prompt fired while a short request is
    mid-decode — its prefill chunks and the short request's fused verify
    rows must share dispatches."""
    import aiohttp

    from dynamo_tpu import tracing
    from dynamo_tpu.backends.mocker import run_mocker
    from dynamo_tpu.frontend.main import run_frontend
    from dynamo_tpu.llm.mocker import MockEngineArgs
    from dynamo_tpu.runtime import DistributedRuntime
    from dynamo_tpu.runtime.store import StoreServer

    tracing.configure(enabled=True, sample=1.0)
    collector = tracing.get_collector()
    collector.clear()

    if mixed:
        args = MockEngineArgs(
            num_kv_blocks=8192,
            block_size=8,
            megastep_k=megastep_k,
            scheduling="chunked",
            prefill_chunk=256,
            spec_decode="ngram",
            spec_k=4,
            speedup_ratio=50.0,
        )
    else:
        args = MockEngineArgs(
            num_kv_blocks=8192,
            block_size=8,
            megastep_k=megastep_k,
            speedup_ratio=50.0,
        )

    store = StoreServer()
    await store.start()
    worker_rt = await DistributedRuntime.create(store.address)
    served = asyncio.Event()
    engines: list = []
    worker = asyncio.create_task(
        run_mocker(
            worker_rt,
            model_name="mock",
            engine_args=args,
            served_event=served,
            engine_out=engines,
        )
    )
    await asyncio.wait_for(served.wait(), 30)
    front_rt = await DistributedRuntime.create(store.address)
    ready = asyncio.Event()
    services: list = []
    frontend = asyncio.create_task(
        run_frontend(
            front_rt, http_host="127.0.0.1", http_port=0,
            router_mode="kv", ready_event=ready, service_out=services,
        )
    )
    await asyncio.wait_for(ready.wait(), 30)
    base = f"http://127.0.0.1:{services[0].port}"

    async with aiohttp.ClientSession() as s:
        for _ in range(200):
            async with s.get(f"{base}/v1/models") as r:
                if (await r.json())["data"]:
                    break
            await asyncio.sleep(0.05)
        else:
            raise TimeoutError("model never appeared on frontend")

        url = f"{base}/v1/chat/completions"
        if mixed:
            # Short request first; once its stream is flowing, fire the
            # LONG prompt (2000 byte-tokens, chunked at 256/step) so its
            # prefill chunks share iterations with the short request's
            # fused decode/verify rows.
            short_task = asyncio.create_task(
                stream_text(s, url, _chat_body("megastep mixed smoke", 96))
            )
            await asyncio.sleep(0.15)  # short request is mid-decode
            long_text = await stream_text(
                s, url, _chat_body("long " * 500, 48)
            )
            texts = [await short_task, long_text]
        else:
            texts = [
                await stream_text(s, url, _chat_body("megastep smoke test", 32))
            ]

    stats = dict(engines[0].scheduler_stats()) if engines else {}
    megasteps = [
        sp for sp in collector.stats() if sp.name == "engine_megastep"
    ]
    if megastep_k > 1:
        assert megasteps, "k>1 worker recorded no engine_megastep spans"
        assert all(
            sp.attrs.get("inner_steps", 0) > 1 for sp in megasteps
        ), "engine_megastep span missing the inner-iteration count"
        assert all(
            "fused_shapes" in sp.attrs for sp in megasteps
        ), "engine_megastep span missing the fused_shapes attr"
    else:
        assert not megasteps, "k=1 worker reported fused megasteps"

    for task in (worker, frontend):
        task.cancel()
    for rt in (worker_rt, front_rt):
        await rt.shutdown()
    await store.stop()
    return texts, stats


async def run() -> None:
    # Phase 1 (ISSUE 7): decode-only fusion, byte-identical streams.
    texts_k8, _ = await run_one(8, mixed=False)
    texts_k1, _ = await run_one(1, mixed=False)
    assert texts_k8[0], "megastep deployment streamed nothing"
    assert texts_k8 == texts_k1, (
        f"megastep k=8 stream diverged from k=1:\n  k8: {texts_k8!r}\n"
        f"  k1: {texts_k1!r}"
    )

    # Phase 2 (ISSUE 12): chunked + spec mixed traffic. Byte-identical
    # streams, >= 1 FUSED mixed dispatch on the gauges, zero forced-k=1
    # batches (the watch-overflow path never applies to these requests).
    mixed_k8, st8 = await run_one(8, mixed=True)
    mixed_k1, st1 = await run_one(1, mixed=True)
    assert all(mixed_k8), "mixed-traffic deployment streamed nothing"
    assert mixed_k8 == mixed_k1, (
        f"universal megastep k=8 mixed stream diverged from k=1:\n"
        f"  k8: {mixed_k8!r}\n  k1: {mixed_k1!r}"
    )
    assert st8.get("megastep_dispatches", 0) >= 1, st8
    assert st8.get("fused_mixed_dispatches", 0) >= 1, (
        "mixed traffic produced no fused mixed dispatches", st8,
    )
    assert st8.get("megastep_forced_single", 0) == 0, (
        "a batch was forced back to k=1 outside the watch-overflow path",
        st8,
    )
    assert st1.get("megastep_dispatches", 0) == 0, st1

    print(
        f"megastep-smoke OK: decode-only {len(texts_k8[0])} chars + mixed "
        f"{sum(len(t) for t in mixed_k8)} chars bit-identical k=8 vs k=1; "
        f"{st8['fused_mixed_dispatches']} fused mixed dispatches, "
        f"0 forced-single", flush=True,
    )


def main() -> int:
    asyncio.run(run())
    return 0


if __name__ == "__main__":
    sys.exit(main())
