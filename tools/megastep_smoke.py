"""Megastep smoke: a mocker-backed frontend with ``--megastep-k 8``
streams BIT-IDENTICAL output to a twin deployment running single-step
(k=1), and the k=8 worker records ``engine_megastep`` stat spans (the
per-dispatch fusion evidence) that the k=1 worker must not.

This is the user-visible contract of device-side multi-step decode
(ISSUE 7): fusing k decode iterations into one device dispatch changes
HOW OFTEN the host and device talk — one fixed dispatch overhead per k
tokens instead of per token — never which tokens are emitted. The same
greedy request runs against a k=8 deployment and a k=1 deployment
(fresh store + worker + frontend each, so no state leaks between the
two), and the full streamed text must match byte for byte.

CI usage (`.github/workflows/ci.yml` megastep-smoke step) and local:

    python tools/megastep_smoke.py
"""

from __future__ import annotations

import asyncio
import sys
from pathlib import Path

# Runnable straight from a checkout (CI also pip-installs the package).
sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


async def stream_text(session, url: str, body: dict) -> str:
    """POST a streaming chat completion; return the concatenated content."""
    import json

    parts: list[str] = []
    async with session.post(url, json=body) as resp:
        assert resp.status == 200, await resp.text()
        async for raw in resp.content:
            line = raw.decode("utf-8", "replace").strip()
            if not line.startswith("data:") or "[DONE]" in line:
                continue
            chunk = json.loads(line[len("data:"):])
            for choice in chunk.get("choices", []):
                parts.append((choice.get("delta") or {}).get("content") or "")
    return "".join(parts)


async def run_one(megastep_k: int) -> tuple[str, int]:
    """Boot store + mocker (megastep k) + frontend, stream one greedy
    request, and return (streamed text, engine_megastep span count)."""
    import aiohttp

    from dynamo_tpu import tracing
    from dynamo_tpu.backends.mocker import run_mocker
    from dynamo_tpu.frontend.main import run_frontend
    from dynamo_tpu.llm.mocker import MockEngineArgs
    from dynamo_tpu.runtime import DistributedRuntime
    from dynamo_tpu.runtime.store import StoreServer

    tracing.configure(enabled=True, sample=1.0)
    collector = tracing.get_collector()
    collector.clear()

    store = StoreServer()
    await store.start()
    worker_rt = await DistributedRuntime.create(store.address)
    served = asyncio.Event()
    worker = asyncio.create_task(
        run_mocker(
            worker_rt,
            model_name="mock",
            engine_args=MockEngineArgs(
                num_kv_blocks=8192,
                block_size=8,
                megastep_k=megastep_k,
                speedup_ratio=50.0,
            ),
            served_event=served,
        )
    )
    await asyncio.wait_for(served.wait(), 30)
    front_rt = await DistributedRuntime.create(store.address)
    ready = asyncio.Event()
    services: list = []
    frontend = asyncio.create_task(
        run_frontend(
            front_rt, http_host="127.0.0.1", http_port=0,
            router_mode="kv", ready_event=ready, service_out=services,
        )
    )
    await asyncio.wait_for(ready.wait(), 30)
    base = f"http://127.0.0.1:{services[0].port}"

    async with aiohttp.ClientSession() as s:
        for _ in range(200):
            async with s.get(f"{base}/v1/models") as r:
                if (await r.json())["data"]:
                    break
            await asyncio.sleep(0.05)
        else:
            raise TimeoutError("model never appeared on frontend")

        text = await stream_text(
            s, f"{base}/v1/chat/completions",
            {
                "model": "mock",
                "messages": [{"role": "user", "content": "megastep smoke test"}],
                "max_tokens": 32,
                "temperature": 0,
                "stream": True,
            },
        )

    megasteps = [
        sp for sp in collector.stats() if sp.name == "engine_megastep"
    ]
    if megastep_k > 1:
        assert megasteps, "k>1 worker recorded no engine_megastep spans"
        assert all(
            sp.attrs.get("inner_steps", 0) > 1 for sp in megasteps
        ), "engine_megastep span missing the inner-iteration count"
    else:
        assert not megasteps, "k=1 worker reported fused megasteps"

    for task in (worker, frontend):
        task.cancel()
    for rt in (worker_rt, front_rt):
        await rt.shutdown()
    await store.stop()
    return text, len(megasteps)


async def run() -> None:
    text_k8, megasteps = await run_one(8)
    text_k1, _ = await run_one(1)
    assert text_k8, "megastep deployment streamed nothing"
    assert text_k8 == text_k1, (
        f"megastep k=8 stream diverged from k=1:\n  k8: {text_k8!r}\n"
        f"  k1: {text_k1!r}"
    )
    print(
        f"megastep-smoke OK: {len(text_k8)} chars bit-identical k=8 vs "
        f"k=1; {megasteps} engine_megastep spans recorded", flush=True,
    )


def main() -> int:
    asyncio.run(run())
    return 0


if __name__ == "__main__":
    sys.exit(main())
