"""Fleet-observability smoke: two mocker workers behind the real OpenAI
frontend with the fleet aggregator EMBEDDED (the default `--fleet-obs on`
deployment shape of ISSUE 13).

Asserts the user-visible contract:

- the frontend's /metrics carries BOTH workers' snapshot-fed series with
  ``worker_id`` labels plus ``dynamo_fleet_*`` rollups (sum/max/p50/p99
  across live workers) — the fleet view composed from the event plane,
  no per-worker scraping;
- ``/fleet`` renders the per-tenant SLO breakdown (requests, TTFT/TPOT
  percentiles, attainment, phase means) stitched from the request's
  trace spans;
- a chaos-killed worker leaves a PARSEABLE flight-recorder dump whose
  step records carry the victim's final lane cursors — and the client's
  stream still completes (migration replays it on the survivor).

CI usage (`.github/workflows/ci.yml` obs-smoke step) and local:

    python tools/obs_smoke.py
"""

from __future__ import annotations

import asyncio
import json
import os
import sys
import tempfile
from pathlib import Path

# Runnable straight from a checkout (CI also pip-installs the package).
sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

FLIGHT_DIR = os.path.join(tempfile.gettempdir(), "dynamo_obs_smoke_flight")
os.environ["DYN_FLIGHT_DIR"] = FLIGHT_DIR

BODY = {
    "model": "mock",
    "messages": [{"role": "user", "content": "fleet observability smoke"}],
    "max_tokens": 8,
    "temperature": 0,
    "stream": False,
}


async def _boot():
    """Store + 2 mocker workers (fast snapshot cadence) + the real
    frontend with the aggregator embedded."""
    from dynamo_tpu.backends.mocker import run_mocker
    from dynamo_tpu.frontend.main import run_frontend
    from dynamo_tpu.llm.mocker import MockEngineArgs
    from dynamo_tpu.runtime import DistributedRuntime
    from dynamo_tpu.runtime.store import StoreServer

    store = StoreServer()
    await store.start()
    runtimes, tasks = [], []
    for _ in range(2):
        rt = await DistributedRuntime.create(store.address)
        served = asyncio.Event()
        tasks.append(
            asyncio.create_task(
                run_mocker(
                    rt, model_name="mock",
                    engine_args=MockEngineArgs(
                        num_kv_blocks=1024, block_size=8, speedup_ratio=50.0
                    ),
                    served_event=served, obs_interval_s=0.1,
                )
            )
        )
        await asyncio.wait_for(served.wait(), 30)
        runtimes.append(rt)
    front_rt = await DistributedRuntime.create(store.address)
    ready = asyncio.Event()
    services: list = []
    tasks.append(
        asyncio.create_task(
            run_frontend(
                front_rt, http_host="127.0.0.1", http_port=0,
                router_mode="round_robin", ready_event=ready,
                service_out=services, obs_interval_s=0.1,
            )
        )
    )
    await asyncio.wait_for(ready.wait(), 30)
    wids = [rt.primary_lease_id for rt in runtimes]
    return (
        (store, runtimes + [front_rt], tasks),
        f"http://127.0.0.1:{services[0].port}",
        wids,
    )


async def _teardown(handles) -> None:
    store, runtimes, tasks = handles
    for t in tasks:
        t.cancel()
    for rt in runtimes:
        try:
            await rt.shutdown()
        except (ConnectionError, OSError):
            pass
    await store.stop()


async def _wait_model(s, base: str) -> None:
    for _ in range(200):
        async with s.get(f"{base}/v1/models") as r:
            if (await r.json())["data"]:
                return
        await asyncio.sleep(0.05)
    raise TimeoutError("model never appeared on frontend")


async def run() -> None:
    import aiohttp

    from dynamo_tpu.runtime import chaos
    from dynamo_tpu.runtime.chaos import ChaosPlan, ChaosRule

    for f in Path(FLIGHT_DIR).glob("flight-*.json") if Path(FLIGHT_DIR).exists() else []:
        f.unlink()

    handles, base, wids = await _boot()
    try:
        async with aiohttp.ClientSession() as s:
            await _wait_model(s, base)

            # Phase 1: traffic to both workers (round robin), then the
            # fleet /metrics must compose BOTH workers' series + rollups.
            for _ in range(4):
                async with s.post(
                    f"{base}/v1/chat/completions", json=dict(BODY),
                    headers={"x-tenant-id": "smoke"},
                ) as r:
                    assert r.status == 200, await r.text()
            text = ""
            for _ in range(100):
                async with s.get(f"{base}/metrics") as r:
                    assert r.status == 200
                    text = await r.text()
                if all(f'worker_id="{w}"' in text for w in wids):
                    break
                await asyncio.sleep(0.1)
            for w in wids:
                assert f'worker_id="{w}"' in text, (
                    f"fleet /metrics missing worker {w}'s series"
                )
            for stat in ("sum", "max", "p50", "p99"):
                assert (
                    f'dynamo_fleet_scheduler_running_seqs{{namespace="dynamo",'
                    f'service="engine",stat="{stat}"}}' in text
                ), f"fleet rollup stat={stat} missing"

            # Phase 2: /fleet renders the per-tenant SLO breakdown.
            fleet = {}
            for _ in range(100):
                async with s.get(f"{base}/fleet") as r:
                    assert r.status == 200
                    fleet = (await r.json()).get("dynamo", {})
                slo = fleet.get("slo", {}).get("tenants", {}).get("smoke", {})
                if slo.get("requests"):
                    break
                await asyncio.sleep(0.1)
            smoke = fleet["slo"]["tenants"]["smoke"]
            assert smoke["requests"] >= 1, fleet
            assert smoke["ttft_p50_ms"] > 0
            for phase in ("queue", "prefill_compute", "decode"):
                assert phase in smoke["phase_mean_ms"], smoke
            assert sorted(fleet["live_workers"]) == sorted(wids)

            # Phase 3: chaos-kill one worker mid-decode; the stream must
            # still complete (migration) and the victim must leave a
            # parseable flight-recorder dump.
            kill = ChaosRule(point="engine.step", action="kill",
                             match="mock", after=12, count=1)
            chaos.install(ChaosPlan([kill]))
            try:
                body = dict(BODY, max_tokens=48)
                async with s.post(
                    f"{base}/v1/chat/completions", json=body,
                    headers={"x-tenant-id": "smoke"},
                ) as r:
                    assert r.status == 200, await r.text()
                    out = await r.json()
                # The kill rule fires exactly once (count=1); if it
                # somehow hasn't yet, one more request forces the
                # victim's engine loop past `after`.
                if kill.fires < 1:
                    async with s.post(
                        f"{base}/v1/chat/completions", json=body,
                        headers={"x-tenant-id": "smoke"},
                    ) as r:
                        assert r.status == 200, await r.text()
                        out = await r.json()
                assert kill.fires >= 1, "chaos kill never fired"
                assert out["choices"][0]["message"]["content"], (
                    "migrated stream returned no content"
                )
            finally:
                chaos.uninstall()
            dumps = sorted(Path(FLIGHT_DIR).glob("flight-*chaos_kill*.json"))
            assert dumps, (
                f"chaos kill left no flight-recorder artifact in {FLIGHT_DIR}"
            )
            payload = json.loads(dumps[0].read_text())
            assert payload["reason"] == "chaos_kill"
            steps = [
                r for r in payload["records"] if r.get("kind") == "step"
            ]
            assert steps, "flight dump carries no step records"
            assert any(r.get("lanes") for r in steps), (
                "no lane cursors in the victim's step records"
            )
            assert "token_ids" not in json.dumps(payload), "dump not redacted"
    finally:
        await _teardown(handles)

    print(
        f"obs-smoke OK: fleet /metrics composed {len(wids)} workers' series "
        f"+ rollups, /fleet rendered the SLO breakdown "
        f"({smoke['requests']} request(s), ttft_p50 {smoke['ttft_p50_ms']} "
        f"ms), chaos kill left a parseable redacted flight dump "
        f"({len(steps)} step records)",
        flush=True,
    )


def main() -> int:
    asyncio.run(run())
    return 0


if __name__ == "__main__":
    sys.exit(main())
