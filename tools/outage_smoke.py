"""Outage smoke: black out the control-plane store mid-stream and assert
serving is unaffected, then restart it and assert clean reconvergence.

The end-to-end degraded-mode contract (ISSUE 15): a mocker-backed
frontend with two workers streams a greedy request; the store server is
STOPPED after the first few tokens (every session in the deployment goes
dark at once — the etcd/NATS-blackout twin); the in-flight stream must
complete byte-identical to a no-fault run, a NEW request issued during
the blackout must succeed on cached discovery state, and the frontend's
/health must report ``degraded`` (still 200 — load balancers keep
routing). After the store restarts on the same port, both workers'
session replays re-register their instances within one lease TTL,
/health returns to ``healthy``, and the frontend's /metrics shows
``store_connected 1`` with ``store_session_rebuilds_total >= 1``.

CI usage (`.github/workflows/ci.yml` outage-smoke step) and local:

    python tools/outage_smoke.py
"""

from __future__ import annotations

import asyncio
import sys
from pathlib import Path

# Runnable straight from a checkout (CI also pip-installs the package).
sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


async def stream_text(session, url: str, body: dict, on_chunk=None) -> str:
    """POST a streaming chat completion; return the concatenated content,
    calling ``on_chunk(parts)`` after every content delta."""
    import json

    parts: list[str] = []
    async with session.post(url, json=body) as resp:
        assert resp.status == 200, await resp.text()
        async for raw in resp.content:
            line = raw.decode("utf-8", "replace").strip()
            if not line.startswith("data:") or "[DONE]" in line:
                continue
            chunk = json.loads(line[len("data:"):])
            for choice in chunk.get("choices", []):
                piece = (choice.get("delta") or {}).get("content") or ""
                if piece:
                    parts.append(piece)
                    if on_chunk is not None:
                        await on_chunk(parts)
    return "".join(parts)


def chat_body(content: str, max_tokens: int) -> dict:
    return {
        "model": "mock",
        "messages": [{"role": "user", "content": content}],
        "max_tokens": max_tokens,
        "temperature": 0,
        "stream": True,
    }


async def boot_worker(store_address: str, args) -> tuple:
    from dynamo_tpu.backends.mocker import run_mocker
    from dynamo_tpu.runtime import DistributedRuntime

    rt = await DistributedRuntime.create(store_address, lease_ttl=5.0)
    served = asyncio.Event()
    task = asyncio.create_task(
        run_mocker(rt, model_name="mock", engine_args=args, served_event=served)
    )
    await asyncio.wait_for(served.wait(), 30)
    return rt, task


async def wait_health(session, base: str, want: str, budget_s: float = 30.0) -> dict:
    deadline = asyncio.get_running_loop().time() + budget_s
    last: dict = {}
    while asyncio.get_running_loop().time() < deadline:
        try:
            async with session.get(f"{base}/health") as r:
                last = await r.json()
                if last.get("status") == want:
                    return last
        except OSError:
            pass
        await asyncio.sleep(0.1)
    raise AssertionError(f"/health never reached {want!r}; last: {last}")


async def run_blackout(baseline: str) -> None:
    import aiohttp

    from dynamo_tpu.frontend.main import run_frontend
    from dynamo_tpu.llm.mocker import MockEngineArgs
    from dynamo_tpu.runtime import DistributedRuntime
    from dynamo_tpu.runtime.store import StoreClient, StoreServer

    # ~20ms per decode iteration so the blackout lands mid-stream.
    args = MockEngineArgs(
        num_kv_blocks=2048, block_size=8, decode_us_per_seq=20000.0
    )
    store = StoreServer()
    await store.start()
    port = store.port
    workers = [await boot_worker(store.address, args) for _ in range(2)]
    front_rt = await DistributedRuntime.create(store.address)
    ready = asyncio.Event()
    services: list = []
    frontend = asyncio.create_task(
        run_frontend(
            front_rt, http_host="127.0.0.1", http_port=0,
            router_mode="kv", ready_event=ready, service_out=services,
        )
    )
    await asyncio.wait_for(ready.wait(), 30)
    base = f"http://127.0.0.1:{services[0].port}"

    blacked_out = asyncio.Event()

    async def maybe_black_out(parts: list[str]) -> None:
        if not blacked_out.is_set() and len(parts) >= 3:
            blacked_out.set()
            await store.stop()  # every session in the deployment goes dark

    async with aiohttp.ClientSession() as s:
        for _ in range(200):
            async with s.get(f"{base}/v1/models") as r:
                if (await r.json())["data"]:
                    break
            await asyncio.sleep(0.05)
        else:
            raise TimeoutError("model never appeared on frontend")

        # 1. In-flight stream survives the blackout byte-identically.
        text = await stream_text(
            s, f"{base}/v1/chat/completions",
            chat_body("outage smoke test", 16),
            on_chunk=maybe_black_out,
        )
        assert blacked_out.is_set(), "stream finished before the blackout"
        assert text == baseline, (
            "stream through the store blackout diverged from the "
            f"no-fault run:\n  fault : {text!r}\n  clean : {baseline!r}"
        )

        # 2. The frontend reports degraded (200, still routable).
        health = await wait_health(s, base, "degraded")
        assert health["control_plane"]["connected"] is False, health

        # 3. A NEW request during the blackout succeeds on cached routes.
        during = await stream_text(
            s, f"{base}/v1/chat/completions",
            chat_body("routed on cached instances", 8),
        )
        assert during, "new request during the blackout streamed nothing"

        # 4. Store restart: sessions replay, workers re-register within a
        #    lease TTL, /health leaves degraded.
        store2 = StoreServer(port=port)
        await store2.start()
        try:
            probe = await StoreClient.open(store2.address)
            try:
                want = {w[0].primary_lease_id for w in workers}
                for _ in range(200):
                    regs = await probe.kv_get_prefix("/dynamo/instances/")
                    seen = {
                        int(k.rsplit("/", 1)[-1], 16) for k in regs
                    }
                    if want <= seen:
                        break
                    await asyncio.sleep(0.1)
                else:
                    raise AssertionError(
                        f"workers never re-registered; saw {seen}, want {want}"
                    )
            finally:
                await probe.close()

            health = await wait_health(s, base, "healthy")
            assert health["control_plane"]["connected"] is True, health
            assert health["control_plane"]["session_rebuilds"] >= 1, health

            async with s.get(f"{base}/metrics") as r:
                metrics = await r.text()
            assert 'dynamo_store_connected{service="store"} 1.0' in metrics
            assert "dynamo_store_session_rebuilds_total" in metrics
            assert "dynamo_store_outage_seconds" in metrics

            # 5. And the recovered deployment still serves.
            after = await stream_text(
                s, f"{base}/v1/chat/completions",
                chat_body("outage smoke test", 16),
            )
            assert after == baseline, "post-recovery stream diverged"
        finally:
            frontend.cancel()
            for rt, task in workers:
                task.cancel()
                try:
                    await rt.shutdown()
                except (ConnectionError, OSError):
                    pass
            try:
                await front_rt.shutdown()
            except (ConnectionError, OSError):
                pass
            await store2.stop()

    print(
        "outage-smoke OK: stream bit-identical through a store blackout, "
        "new request served on cached routes, /health degraded->healthy, "
        "both workers re-registered after restart", flush=True,
    )


async def run_baseline() -> str:
    """No-fault single run of the same deployment shape: the byte-exact
    reference stream."""
    import aiohttp

    from dynamo_tpu.frontend.main import run_frontend
    from dynamo_tpu.llm.mocker import MockEngineArgs
    from dynamo_tpu.runtime import DistributedRuntime
    from dynamo_tpu.runtime.store import StoreServer

    args = MockEngineArgs(
        num_kv_blocks=2048, block_size=8, decode_us_per_seq=20000.0
    )
    store = StoreServer()
    await store.start()
    workers = [await boot_worker(store.address, args) for _ in range(2)]
    front_rt = await DistributedRuntime.create(store.address)
    ready = asyncio.Event()
    services: list = []
    frontend = asyncio.create_task(
        run_frontend(
            front_rt, http_host="127.0.0.1", http_port=0,
            router_mode="kv", ready_event=ready, service_out=services,
        )
    )
    await asyncio.wait_for(ready.wait(), 30)
    base = f"http://127.0.0.1:{services[0].port}"
    try:
        async with aiohttp.ClientSession() as s:
            for _ in range(200):
                async with s.get(f"{base}/v1/models") as r:
                    if (await r.json())["data"]:
                        break
                await asyncio.sleep(0.05)
            return await stream_text(
                s, f"{base}/v1/chat/completions",
                chat_body("outage smoke test", 16),
            )
    finally:
        frontend.cancel()
        for rt, task in workers:
            task.cancel()
            await rt.shutdown()
        await front_rt.shutdown()
        await store.stop()


async def run() -> None:
    baseline = await run_baseline()
    assert baseline, "baseline deployment streamed nothing"
    await run_blackout(baseline)


def main() -> int:
    asyncio.run(run())
    return 0


if __name__ == "__main__":
    sys.exit(main())


