"""Overload smoke: a mocker frontend under a synthetic burst sheds
cleanly and serves byte-identical streams to the admitted cohort.

The end-to-end contract of the overload-robustness layer (ISSUE 10):
a frontend with a per-tenant rate limit and an in-flight ceiling takes a
10-request burst from one tenant against a deliberately slow worker.
Phase 1 (frontend full): exactly the ceiling admits; every other
rejection is the truthful ``503 queue_full`` (unused rate tokens are
refunded, so the tenant is not double-penalized). Phase 2 (frontend
drained): the tenant's spent bucket answers ``429 rate_limit``. EVERY
rejection is a clean, typed, retryable JSON error with a ``Retry-After``
header, and every admitted stream completes byte-identical to the
unloaded baseline run. The worker's /metrics must report the scheduler
overload gauges (queue limit, fair flag) and the frontend's /metrics
the ``frontend_requests_shed_total`` counters.

CI usage (`.github/workflows/ci.yml` overload-smoke step) and local:

    python tools/overload_smoke.py
"""

from __future__ import annotations

import asyncio
import json
import sys
from pathlib import Path

# Runnable straight from a checkout (CI also pip-installs the package).
sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


async def post_chat(session, url: str, body: dict, tenant: str):
    """POST one streaming chat completion; returns (status, text,
    retry_after, error_obj)."""
    parts: list[str] = []
    async with session.post(
        url, json=body, headers={"x-tenant-id": tenant}
    ) as resp:
        if resp.status != 200:
            err = (await resp.json())["error"]
            return resp.status, "", resp.headers.get("Retry-After"), err
        async for raw in resp.content:
            line = raw.decode("utf-8", "replace").strip()
            if not line.startswith("data:") or "[DONE]" in line:
                continue
            chunk = json.loads(line[len("data:"):])
            for choice in chunk.get("choices", []):
                piece = (choice.get("delta") or {}).get("content") or ""
                if piece:
                    parts.append(piece)
        return 200, "".join(parts), None, None


async def run() -> None:
    import aiohttp

    from dynamo_tpu.backends.mocker.main import run_mocker
    from dynamo_tpu.frontend.main import run_frontend
    from dynamo_tpu.llm.admission import AdmissionConfig
    from dynamo_tpu.llm.mocker import MockEngineArgs
    from dynamo_tpu.runtime import DistributedRuntime
    from dynamo_tpu.runtime.store import StoreServer

    store = StoreServer()
    await store.start()
    # Slow decode (~20 ms/token) so the burst overlaps in flight; fair
    # scheduling + a queue bound armed to prove the knobs exist end to
    # end (the burst is admission-limited before the worker queue is).
    worker_rt = await DistributedRuntime.create(store.address)
    served = asyncio.Event()
    worker = asyncio.create_task(
        run_mocker(
            worker_rt, model_name="mock",
            engine_args=MockEngineArgs(
                num_kv_blocks=2048, block_size=8,
                decode_us_per_seq=20000.0,
                fair_scheduling=True, max_waiting=64,
            ),
            served_event=served,
        )
    )
    await asyncio.wait_for(served.wait(), 30)
    front_rt = await DistributedRuntime.create(store.address)
    ready = asyncio.Event()
    services: list = []
    frontend = asyncio.create_task(
        run_frontend(
            front_rt, http_host="127.0.0.1", http_port=0, router_mode="kv",
            ready_event=ready, service_out=services,
            admission=AdmissionConfig(
                tenant_rate=0.02, tenant_burst=3, max_inflight=2
            ),
        )
    )
    await asyncio.wait_for(ready.wait(), 30)
    base = f"http://127.0.0.1:{services[0].port}"
    body = {
        "model": "mock",
        "messages": [{"role": "user", "content": "overload smoke"}],
        "max_tokens": 8,
        "temperature": 0,
        "stream": True,
    }

    try:
        async with aiohttp.ClientSession() as s:
            for _ in range(200):
                async with s.get(f"{base}/v1/models") as r:
                    if (await r.json())["data"]:
                        break
                await asyncio.sleep(0.05)
            else:
                raise TimeoutError("model never appeared on frontend")
            url = f"{base}/v1/chat/completions"

            # Unloaded baseline (its own tenant: bucket isolation).
            status, baseline, _, _ = await post_chat(s, url, body, "baseline")
            assert status == 200 and baseline, "baseline stream failed"

            # Phase 1 — ceiling-bound burst: 10 concurrent requests, one
            # tenant, against ceiling 2. Exactly 2 admit; while the
            # frontend is FULL every other rejection is the truthful
            # 503 queue_full (unused rate tokens are refunded — the
            # tenant is not double-penalized for capacity it never got).
            results = await asyncio.gather(
                *(post_chat(s, url, body, "bursty") for _ in range(10))
            )
            statuses = sorted(st for st, *_ in results)
            n200 = statuses.count(200)
            n503 = statuses.count(503)
            assert n200 == 2, f"expected 2 admissions, got {n200} ({statuses})"
            assert n503 == 8, f"expected 8 ceiling sheds, got {statuses}"
            for st, text, retry_after, err in results:
                if st == 200:
                    assert text == baseline, (
                        "admitted stream diverged from the unloaded run:\n"
                        f"  loaded : {text!r}\n  clean  : {baseline!r}"
                    )
                else:
                    assert retry_after is not None, f"{st} missing Retry-After"
                    assert err["retryable"] is True, err
                    assert err["code"] == "queue_full", err

            # Phase 2 — rate-bound burst: the frontend has drained, so
            # the same tenant's spent bucket (2 of burst 3 consumed by
            # the admitted requests; refill 0.02/s is negligible on any
            # CI timeline) now answers 429.
            results2 = await asyncio.gather(
                *(post_chat(s, url, body, "bursty") for _ in range(3))
            )
            statuses2 = sorted(st for st, *_ in results2)
            n429 = statuses2.count(429)
            assert statuses2.count(200) == 1 and n429 == 2, (
                f"expected 1x200 + 2x429 after drain, got {statuses2}"
            )
            for st, text, retry_after, err in results2:
                if st == 200:
                    assert text == baseline
                else:
                    assert retry_after is not None and err["retryable"] is True
                    assert err["code"] == "rate_limit", err

            # Overload observability: shed counters on the frontend,
            # scheduler overload gauges on the worker.
            async with s.get(f"{base}/metrics") as r:
                front_metrics = await r.text()
            assert "frontend_requests_shed_total" in front_metrics
            assert 'reason="rate_limit"' in front_metrics
            status_port = worker_rt.status.port if worker_rt.status else None
            if status_port:
                async with s.get(
                    f"http://127.0.0.1:{status_port}/metrics"
                ) as r:
                    worker_metrics = await r.text()
                assert "scheduler_queue_limit" in worker_metrics
                assert "scheduler_fair_enabled" in worker_metrics
    finally:
        frontend.cancel()
        worker.cancel()
        for t in (frontend, worker):
            try:
                await t
            except (asyncio.CancelledError, ConnectionError, OSError):
                pass
        for rt in (front_rt, worker_rt):
            try:
                await rt.shutdown()
            except (ConnectionError, OSError):
                pass
        await store.stop()

    print(
        "overload-smoke OK: 2/10 burst requests admitted byte-identical "
        f"to the unloaded run; {n503}x503 (ceiling) + {n429}x429 (rate, "
        "post-drain) all typed, retryable, with Retry-After; shed "
        "counters exported",
        flush=True,
    )


def main() -> int:
    asyncio.run(run())
    return 0


if __name__ == "__main__":
    sys.exit(main())
