"""Cluster-KV-pool smoke: two mocker workers behind the real OpenAI
frontend (KV routing); a shared prompt is seeded on whichever worker the
router picks, then the same prompt is re-sent with router temperature
sampling until routing lands on the OTHER worker — which pulls the
cached prefix from its peer over the dataplane instead of recomputing.

Asserts the user-visible contract of ISSUE 11:

- at least one peer pull SUCCEEDED (the ``kv_pool_peer_pulls_succeeded_
  total`` gauge on a worker's /metrics moved), with zero fallbacks;
- every streamed completion is byte-identical to a single-worker
  deployment's stream of the same request (the pool changes WHERE the
  prefix comes from, never which tokens stream).

CI usage (`.github/workflows/ci.yml` peer-pool-smoke step) and local:

    python tools/peer_pool_smoke.py
"""

from __future__ import annotations

import asyncio
import sys
from pathlib import Path

# Runnable straight from a checkout (CI also pip-installs the package).
sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from tools.megastep_smoke import stream_text  # noqa: E402

PROMPT = "cluster kv pool smoke " * 40  # long enough to span many blocks
BODY = {
    "model": "mock",
    "messages": [{"role": "user", "content": PROMPT}],
    "max_tokens": 24,
    "temperature": 0,
    "stream": True,
}


def _engine_args():
    from dynamo_tpu.llm.mocker import MockEngineArgs

    return MockEngineArgs(
        num_kv_blocks=4096,
        block_size=8,
        speedup_ratio=50.0,
        kv_pull_us_per_block=20.0,
    )


async def _boot(n_workers: int):
    """Store + n mocker workers (each with a live status server) + a KV
    frontend; returns (handles-to-teardown, base_url, status_ports)."""
    from dynamo_tpu.backends.mocker import run_mocker
    from dynamo_tpu.frontend.main import run_frontend
    from dynamo_tpu.runtime import DistributedRuntime
    from dynamo_tpu.runtime.status_server import SystemStatusServer
    from dynamo_tpu.runtime.store import StoreServer

    store = StoreServer()
    await store.start()
    runtimes, tasks, statuses = [], [], []
    for _ in range(n_workers):
        rt = await DistributedRuntime.create(store.address)
        status = SystemStatusServer(host="127.0.0.1", port=0)
        await status.start()
        rt.status = status
        statuses.append(status)
        served = asyncio.Event()
        tasks.append(
            asyncio.create_task(
                run_mocker(
                    rt, model_name="mock", engine_args=_engine_args(),
                    served_event=served,
                )
            )
        )
        await asyncio.wait_for(served.wait(), 30)
        runtimes.append(rt)
    front_rt = await DistributedRuntime.create(store.address)
    runtimes.append(front_rt)
    ready = asyncio.Event()
    services: list = []
    tasks.append(
        asyncio.create_task(
            run_frontend(
                front_rt, http_host="127.0.0.1", http_port=0,
                router_mode="kv", ready_event=ready, service_out=services,
            )
        )
    )
    await asyncio.wait_for(ready.wait(), 30)
    return (store, runtimes, tasks, statuses), f"http://127.0.0.1:{services[0].port}"


async def _teardown(handles) -> None:
    store, runtimes, tasks, statuses = handles
    for t in tasks:
        t.cancel()
    for rt in runtimes:
        await rt.shutdown()
    for st in statuses:
        await st.stop()
    await store.stop()


async def _wait_model(s, base: str) -> None:
    for _ in range(200):
        async with s.get(f"{base}/v1/models") as r:
            if (await r.json())["data"]:
                return
        await asyncio.sleep(0.05)
    raise TimeoutError("model never appeared on frontend")


def _gauge(metrics: str, name: str) -> float:
    for line in metrics.splitlines():
        if line.startswith(name):
            return float(line.rsplit(None, 1)[-1])
    raise AssertionError(f"gauge {name!r} not on /metrics")


async def run() -> None:
    import aiohttp

    # Reference: a single-worker deployment's stream of the same request.
    handles, base = await _boot(1)
    try:
        async with aiohttp.ClientSession() as s:
            await _wait_model(s, base)
            want = await stream_text(s, f"{base}/v1/chat/completions", dict(BODY))
    finally:
        await _teardown(handles)
    assert want, "single-worker reference streamed nothing"

    # The pool fleet: 2 workers. Request 1 seeds one of them; repeats with
    # router temperature sampling eventually land on the other, which must
    # serve its prefill via a peer pull.
    handles, base = await _boot(2)
    try:
        statuses = handles[3]
        async with aiohttp.ClientSession() as s:
            await _wait_model(s, base)
            texts = [await stream_text(s, f"{base}/v1/chat/completions", dict(BODY))]
            pulls = 0.0
            for _ in range(24):
                body = dict(BODY, dyn={"router": {"router_temperature": 2.0}})
                texts.append(
                    await stream_text(s, f"{base}/v1/chat/completions", body)
                )
                metrics = []
                for st in statuses:
                    async with s.get(
                        f"http://127.0.0.1:{st.port}/metrics"
                    ) as r:
                        assert r.status == 200
                        metrics.append(await r.text())
                pulls = sum(
                    _gauge(m, "dynamo_kv_pool_peer_pulls_succeeded_total")
                    for m in metrics
                )
                if pulls >= 1:
                    break
            assert pulls >= 1, (
                "no peer pull succeeded across 24 temperature-sampled "
                "requests (two-worker fleet)"
            )
            fallbacks = sum(
                _gauge(m, "dynamo_kv_pool_peer_pulls_fallback_total")
                for m in metrics
            )
            blocks = sum(
                _gauge(m, "dynamo_kv_pool_blocks_pulled_total") for m in metrics
            )
            assert fallbacks == 0, f"{fallbacks} pulls fell back in a healthy fleet"
            assert blocks >= 1, "a successful pull imported no blocks"
    finally:
        await _teardown(handles)

    bad = [i for i, t in enumerate(texts) if t != want]
    assert not bad, (
        f"streams diverged from the single-worker reference at request(s) "
        f"{bad}:\n  want: {want!r}\n  got:  {texts[bad[0]]!r}"
    )
    print(
        f"peer-pool-smoke OK: {len(texts)} streams byte-identical to the "
        f"single-worker run; {int(pulls)} peer pull(s), {int(blocks)} "
        f"block(s) imported, 0 fallbacks",
        flush=True,
    )


def main() -> int:
    asyncio.run(run())
    return 0


if __name__ == "__main__":
    sys.exit(main())
