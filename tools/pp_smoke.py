"""Pipeline-parallel smoke: a mocker-backed frontend deployed with
``--pp 2`` (two pipeline stages, fused ``--megastep-k 8`` megasteps)
streams BIT-IDENTICAL output to a twin deployment running unpipelined
(pp=1), the worker's ``engine_megastep`` spans carry the ``pp_stages``
attr (the per-dispatch pipelining evidence), and the ``scheduler_pp_*``
gauges export on /metrics.

This is the user-visible contract of pp on the fast path (ISSUE 20):
pipeline stages change WHERE layers live and how iterations wavefront
through the stage ring — ``k*pp + pp - 1`` ppermute hops amortized over
one fused dispatch instead of ``pp`` hops per token on the
host-rollback baseline — never which tokens a request streams. The real
engine's bit-parity + quantization-composition invariants are pinned by
tests/test_pp_megastep.py; the A/B latency bar by bench.py
run_pp_megastep_ab.

CI usage (`.github/workflows/ci.yml` pp-smoke step) and local:

    python tools/pp_smoke.py
"""

from __future__ import annotations

import asyncio
import sys
from pathlib import Path

# Runnable straight from a checkout (CI also pip-installs the package).
sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from tools.kvquant_smoke import _gauge_value  # noqa: E402
from tools.megastep_smoke import stream_text  # noqa: E402


async def run_one(pp: int) -> tuple[list[str], str, list]:
    """Boot store + mocker (pp stages, megastep k=8) + frontend with a
    live status server, stream two greedy requests, and return
    (streamed texts, the worker's /metrics text, engine_megastep spans).
    """
    import aiohttp

    from dynamo_tpu import tracing
    from dynamo_tpu.backends.mocker import run_mocker
    from dynamo_tpu.frontend.main import run_frontend
    from dynamo_tpu.llm.mocker import MockEngineArgs
    from dynamo_tpu.runtime import DistributedRuntime
    from dynamo_tpu.runtime.status_server import SystemStatusServer
    from dynamo_tpu.runtime.store import StoreServer

    tracing.configure(enabled=True, sample=1.0)
    collector = tracing.get_collector()
    collector.clear()

    store = StoreServer()
    await store.start()
    worker_rt = await DistributedRuntime.create(store.address)
    status = SystemStatusServer(host="127.0.0.1", port=0)
    await status.start()
    worker_rt.status = status  # bind_scheduler_gauges hooks in run_mocker
    served = asyncio.Event()
    worker = asyncio.create_task(
        run_mocker(
            worker_rt,
            model_name="mock",
            engine_args=MockEngineArgs(
                num_kv_blocks=4096,
                block_size=8,
                megastep_k=8,
                pp=pp,
                speedup_ratio=50.0,
            ),
            served_event=served,
        )
    )
    await asyncio.wait_for(served.wait(), 30)
    front_rt = await DistributedRuntime.create(store.address)
    ready = asyncio.Event()
    services: list = []
    frontend = asyncio.create_task(
        run_frontend(
            front_rt, http_host="127.0.0.1", http_port=0,
            router_mode="kv", ready_event=ready, service_out=services,
        )
    )
    await asyncio.wait_for(ready.wait(), 30)
    base = f"http://127.0.0.1:{services[0].port}"

    async with aiohttp.ClientSession() as s:
        for _ in range(200):
            async with s.get(f"{base}/v1/models") as r:
                if (await r.json())["data"]:
                    break
            await asyncio.sleep(0.05)
        else:
            raise TimeoutError("model never appeared on frontend")

        url = f"{base}/v1/chat/completions"
        texts = []
        for content, mt in (("pp smoke test", 32), ("pipeline twin", 48)):
            texts.append(await stream_text(s, url, {
                "model": "mock",
                "messages": [{"role": "user", "content": content}],
                "max_tokens": mt,
                "temperature": 0,
                "stream": True,
            }))
        async with s.get(f"http://127.0.0.1:{status.port}/metrics") as r:
            assert r.status == 200
            metrics = await r.text()

    spans = [sp for sp in collector.stats() if sp.name == "engine_megastep"]
    for task in (worker, frontend):
        task.cancel()
    for rt in (worker_rt, front_rt):
        await rt.shutdown()
    await status.stop()
    await store.stop()
    return texts, metrics, spans


async def run() -> None:
    texts_pp, m_pp, spans_pp = await run_one(2)
    assert all(texts_pp), "pp=2 deployment streamed nothing"
    assert spans_pp, "pp=2 worker recorded no engine_megastep spans"
    assert all(sp.attrs.get("pp_stages") == 2 for sp in spans_pp), (
        "engine_megastep span missing the pp_stages attr"
    )
    assert _gauge_value(m_pp, "dynamo_scheduler_pp_stages") == 2.0
    # k=8 over 2 stages: 16 wavefront items over 16 + 1 rounds.
    occ = _gauge_value(m_pp, "dynamo_scheduler_pp_pipe_occupancy")
    assert abs(occ - 16.0 / 17.0) < 1e-6, occ
    fused = _gauge_value(m_pp, "dynamo_scheduler_pp_fused_dispatches_total")
    assert fused >= 1, "pp=2 worker fused no pp megastep dispatches"
    assert _gauge_value(
        m_pp, "dynamo_scheduler_pp_forced_single_total"
    ) == 0.0, "a decode batch fell back to forced k=1 under pp"

    texts_1, m_1, spans_1 = await run_one(1)
    assert texts_pp == texts_1, (
        f"pp=2 stream diverged from the unpipelined twin:\n"
        f"  pp2: {texts_pp!r}\n  pp1: {texts_1!r}"
    )
    assert all(sp.attrs.get("pp_stages") == 1 for sp in spans_1)
    assert _gauge_value(m_1, "dynamo_scheduler_pp_stages") == 1.0
    assert _gauge_value(m_1, "dynamo_scheduler_pp_pipe_occupancy") == 1.0
    assert _gauge_value(
        m_1, "dynamo_scheduler_pp_fused_dispatches_total"
    ) == 0.0

    print(
        f"pp-smoke OK: {sum(len(t) for t in texts_pp)} chars bit-identical "
        f"pp=2 vs pp=1; {fused:.0f} fused pp dispatches, 0 forced-single, "
        f"pipe occupancy {occ:.4f} on /metrics", flush=True,
    )


def main() -> int:
    asyncio.run(run())
    return 0


if __name__ == "__main__":
    sys.exit(main())
