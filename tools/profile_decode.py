"""Decode-step ablation profiler: where does the step time go?

Builds the same fused decode+sample chain EngineCore compiles (bench.py
shapes: llama3-1b, B=32, ctx ~192) and times variants with individual
stages disabled. The deltas attribute step time to attention kernel,
cache scatter, lm-head/logits, sampler, and the matmul weight stream.
Results feed PERF.md (round-4 perf brief, VERDICT.md #1).

Usage: python tools/profile_decode.py [--batch 32] [--ctx 192]
"""

from __future__ import annotations

import argparse
import time
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from dynamo_tpu.engine.config import EngineConfig, llama3_1b
from dynamo_tpu.engine.model import (
    _dot,
    _interleave_kv,
    _logits,
    init_cache,
    init_params,
    rms_norm,
    rope,
    split_gu,
    split_qkv,
)
from dynamo_tpu.ops.ragged_attention import ragged_paged_attention


def build_forward(cfg, engine, *, attn=True, scatter=True, head=True,
                  dense_attn=False, stacked_cache=False):
    """One decode step over B lanes with stages toggleable. ``dense_attn``
    swaps the Pallas kernel for the pure-XLA gather/softmax reference —
    more raw bytes, but it fuses with the surrounding layer instead of
    paying the custom-call boundary per layer. ``stacked_cache`` times the
    pre-r5 [L, ...] single-array layout: its per-layer slices forced XLA
    to materialize a copy at each Pallas call (measured +1.4 ms/step at
    B=32 — the reason model.init_cache is a per-layer tuple now)."""

    def fwd(params, cache, tokens, block_tables, positions, active):
        B = tokens.shape[0]
        bs = engine.block_size
        sm_scale = cfg.head_dim ** -0.5
        page = jnp.take_along_axis(block_tables, (positions // bs)[:, None], axis=1)[:, 0]
        write_pages = jnp.where(active, page, engine.garbage_block)
        write_offs = positions % bs
        kv_lens = jnp.where(active, positions + 1, 1).astype(jnp.int32)
        cu = jnp.arange(B + 1, dtype=jnp.int32)
        num_seqs = jnp.array([B], jnp.int32)

        x = params["embed"][tokens]
        lp_all = params["layers"]
        for l in range(cfg.num_layers):
            lp = jax.tree.map(lambda a: a[l], lp_all)
            y = rms_norm(x, lp["attn_norm"], cfg.rms_norm_eps)
            qkv = _dot(y, lp["wqkv"]).astype(x.dtype)
            q, k, v = split_qkv(qkv, cfg)
            T = q.shape[0]
            q = rope(q.reshape(T, cfg.num_heads, cfg.head_dim), positions, cfg.rope_theta)
            k = rope(k.reshape(T, cfg.num_kv_heads, cfg.head_dim), positions, cfg.rope_theta)
            kvn = _interleave_kv(k.reshape(T, cfg.kv_size), v, cfg)
            if stacked_cache:
                if scatter:
                    cache = cache.at[l, write_pages, write_offs].set(kvn)
                cache_l = cache[l]
            else:
                cache_l = cache[l]
                if scatter:
                    cache_l = cache_l.at[write_pages, write_offs].set(kvn)
                    cache = cache[:l] + (cache_l,) + cache[l + 1:]
            if attn and dense_attn:
                from dynamo_tpu.ops.ragged_attention import (
                    ragged_paged_attention_ref,
                )

                a = ragged_paged_attention_ref(
                    q, cache_l, kv_lens, block_tables, cu, num_seqs,
                    sm_scale=sm_scale,
                )
            elif attn:
                a = ragged_paged_attention(
                    q, cache_l, kv_lens, block_tables, cu, num_seqs,
                    sm_scale=sm_scale,
                )
            else:
                a = q
            a = a.reshape(T, cfg.q_size)
            x = x + _dot(a, lp["wo"]).astype(x.dtype)
            y = rms_norm(x, lp["mlp_norm"], cfg.rms_norm_eps)
            gu = _dot(y, lp["wgu"])
            g, u = split_gu(gu)
            act = (jax.nn.silu(g) * u).astype(x.dtype)
            x = x + _dot(act, lp["w_down"]).astype(x.dtype)
        x = rms_norm(x, params["final_norm"], cfg.rms_norm_eps)
        if head:
            logits = _logits(x, params, cfg)
            nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        else:
            nxt = tokens
        return nxt, cache

    return fwd


def build_chain(cfg, engine, n_steps, unroll=False, **flags):
    fwd = build_forward(cfg, engine, **flags)

    def chain(params, cache, tokens, block_tables, positions, active):
        step = jnp.asarray(active, jnp.int32)

        def body(carry, i):
            toks, cache = carry
            nxt, cache = fwd(params, cache, toks, block_tables, positions + i * step, active)
            return (nxt, cache), nxt

        if unroll:
            toks, outs = tokens, []
            for i in range(n_steps):
                (toks, cache), nxt = body((toks, cache), jnp.int32(i))
                outs.append(nxt)
            return jnp.stack(outs), cache
        (_, cache), sampled = jax.lax.scan(body, (tokens, cache), jnp.arange(n_steps))
        return sampled, cache

    return jax.jit(chain, donate_argnums=(1,))


def timeit(fn, args, cache, n=5):
    # compile + warm; sync via device->host transfer (on the axon relay
    # platform block_until_ready does not flush the execution queue).
    out, cache = fn(*args[:1], cache, *args[2:])
    np.asarray(out)
    best = float("inf")
    for _ in range(n):
        t0 = time.perf_counter()
        out, cache = fn(*args[:1], cache, *args[2:])
        np.asarray(out)
        best = min(best, time.perf_counter() - t0)
    return best, cache


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=32)
    ap.add_argument("--ctx", type=int, default=192)
    ap.add_argument("--steps", type=int, default=32)
    ap.add_argument("--blocks", type=int, default=512)
    ap.add_argument("--only", default=None, help="run a single variant, e.g. 'full'")
    ap.add_argument("--block-size", type=int, default=32)
    ap.add_argument("--max-model-len", type=int, default=512)
    ap.add_argument("--int8", action="store_true", help="int8 weight-only quant")
    args = ap.parse_args()

    cfg = llama3_1b()
    engine = EngineConfig(
        num_kv_blocks=args.blocks, block_size=args.block_size,
        max_num_seqs=args.batch, max_model_len=args.max_model_len,
        decode_buckets=(args.batch,), decode_chain=args.steps,
    )
    B, n_steps = args.batch, args.steps
    params = init_params(jax.random.PRNGKey(0), cfg)
    if args.int8:
        from dynamo_tpu.engine.model import quantize_params

        params = quantize_params(params)
    rng = np.random.RandomState(0)
    tokens = jnp.asarray(rng.randint(1, cfg.vocab_size, B), jnp.int32)
    positions = jnp.full((B,), args.ctx, jnp.int32)
    bs = engine.block_size
    blocks_per_seq = engine.max_blocks_per_seq
    tables = np.full((B, blocks_per_seq), engine.garbage_block, np.int32)
    need = (args.ctx + n_steps) // bs + 1
    ids = rng.permutation(args.blocks)[: B * need].reshape(B, need)
    tables[:, :need] = ids
    tables = jnp.asarray(tables)
    active = jnp.ones((B,), bool)

    pbytes = cfg.param_bytes()
    kv_tok = cfg.num_layers * cfg.num_kv_heads * cfg.head_dim * 2 * 2
    print(f"# B={B} ctx={args.ctx} steps={n_steps} params={pbytes/1e9:.2f}GB "
          f"kv/tok={kv_tok} backend={jax.default_backend()}")

    variants = [
        ("full", dict()),
        ("full_stacked_cache", dict(stacked_cache=True)),
        ("full_unrolled", dict(unroll=True)),
        ("full_dense_attn", dict(dense_attn=True)),
        ("no_attn", dict(attn=False)),
        ("no_scatter", dict(scatter=False)),
        ("no_head", dict(head=False)),
        ("no_attn_no_scatter", dict(attn=False, scatter=False)),
        # NOTE: variants with head=False AND scatter=False have a loop-
        # invariant scan body at long chains — XLA hoists it and the
        # number measures nothing. Trust matmuls_only at --steps 32 only.
        ("matmuls_only", dict(attn=False, scatter=False, head=False)),
    ]
    if args.only:
        variants = [v for v in variants if v[0] == args.only]
    results = {}
    for name, flags in variants:
        cache = init_cache(cfg, engine)  # per-layer tuple (engine layout)
        if flags.get("stacked_cache"):
            from dynamo_tpu.engine.model import init_cache_stacked

            cache = init_cache_stacked(cfg, engine)
        fn = build_chain(cfg, engine, n_steps, **flags)
        t, cache = timeit(fn, (params, cache, tokens, tables, positions, active), cache)
        del cache
        per_step = t / n_steps * 1e3
        results[name] = per_step
        print(f"{name:22s} {t*1e3:8.2f} ms/chain   {per_step:7.3f} ms/step")

    if args.only:
        return

    # single-step (chain of 1) dispatch overhead
    cache = init_cache(cfg, engine)
    fn1 = build_chain(cfg, engine, 1)
    t1, cache = timeit(fn1, (params, cache, tokens, tables, positions, active), cache)
    del cache
    print(f"{'single_step_chain1':22s} {t1*1e3:8.2f} ms/chain   {t1*1e3:7.3f} ms/step")

    full = results["full"]
    print("\n# attributed ms/step:")
    print(f"  attention kernel : {full - results['no_attn']:.3f}")
    print(f"  cache scatter    : {full - results['no_scatter']:.3f}")
    print(f"  lm head + argmax : {full - results['no_head']:.3f}")
    print(f"  matmul stream    : {results['matmuls_only']:.3f}")
    hbm = float(__import__("os").environ.get("BENCH_HBM_GBPS", 819))
    floor = (pbytes + B * (args.ctx + n_steps / 2) * kv_tok) / (hbm * 1e9) * 1e3
    print(f"  roofline floor   : {floor:.3f}")


if __name__ == "__main__":
    main()
