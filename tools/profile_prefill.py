"""Prefill-wave profiler: where does TTFT go?

Times the engine's ragged prefill program (forward_tokens + fused
sampling) at bench shapes — bucket 2048, 16 sequences of 128 tokens —
and compares against the compute/bandwidth floor. Decode got three
rounds of profiling (PERF.md); TTFT p50 (~570-870 ms across bench
configs) was never attributed. At 1B, a 2048-token wave is ~5.1 TFLOP
(~26 ms at v5e bf16 peak) + one weight stream (~3 ms) — anything far
above that is overhead to find.

Usage: python tools/profile_prefill.py [--bucket 2048] [--seqs 16]
"""

from __future__ import annotations

import argparse
import time

import jax
import jax.numpy as jnp
import numpy as np

from dynamo_tpu.engine.config import EngineConfig, llama3_1b
from dynamo_tpu.engine.model import forward_tokens, init_cache, init_params


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--bucket", type=int, default=2048)
    ap.add_argument("--seqs", type=int, default=16)
    ap.add_argument("--blocks", type=int, default=768)
    ap.add_argument("--reps", type=int, default=5)
    ap.add_argument("--no-attn", action="store_true")
    args = ap.parse_args()

    cfg = llama3_1b()
    T, S = args.bucket, args.seqs
    if T % S:
        raise SystemExit(f"--bucket {T} must be a multiple of --seqs {S}")
    per = T // S  # tokens per sequence
    eng = EngineConfig(
        num_kv_blocks=args.blocks, block_size=32, max_num_seqs=args.seqs,
        max_model_len=max(512, per), prefill_buckets=(args.bucket,),
        decode_buckets=(args.seqs,),
    )
    if per % eng.block_size:
        # The page assignment below tiles whole pages per sequence.
        raise SystemExit(
            f"tokens/seq {per} must be a multiple of block_size {eng.block_size}"
        )
    bs = eng.block_size
    rng = np.random.RandomState(0)

    tokens = jnp.asarray(rng.randint(1, cfg.vocab_size, T), jnp.int32)
    positions = jnp.asarray(np.tile(np.arange(per, dtype=np.int32), S))
    pages_per_seq = -(-per // bs)
    ids = rng.permutation(args.blocks)[: S * pages_per_seq].reshape(S, -1)
    write_pages = jnp.asarray(
        np.repeat(ids, bs, axis=1).reshape(-1)[:T].astype(np.int32)
    )
    write_offs = jnp.asarray(
        np.tile(np.arange(per, dtype=np.int32) % bs, S)
    )
    kv_lens = jnp.full((S,), per, jnp.int32)
    tables = np.full((S, eng.max_blocks_per_seq), eng.garbage_block, np.int32)
    tables[:, :pages_per_seq] = ids
    tables = jnp.asarray(tables)
    cu = jnp.asarray(np.arange(S + 1, dtype=np.int32) * per)
    num_seqs = jnp.asarray([S], jnp.int32)
    last_rows = jnp.asarray(
        (np.arange(S, dtype=np.int32) + 1) * per - 1
    )

    params = init_params(jax.random.PRNGKey(0), cfg)

    if args.no_attn:
        # Attribution variant: identity attention (same matmuls/scatter).
        import dynamo_tpu.ops.ragged_attention as ra

        ra.ragged_paged_attention = (
            lambda q, *a, **kw: q
        )
        import dynamo_tpu.engine.model as _m

        _m.ragged_paged_attention = ra.ragged_paged_attention

    def wave(p, c, tok):
        logits, c = forward_tokens(
            p, c, tok, positions, write_pages, write_offs, kv_lens,
            tables, cu, num_seqs, last_rows, cfg, eng, None,
        )
        # Sample on device like the engine's fused program: the host
        # fetch is [S] ints, not [S, V] logits (8 MB of logits over the
        # relay's ~MB/s host link would dominate the measurement).
        return jnp.argmax(logits, axis=-1).astype(jnp.int32), c

    fwd = jax.jit(wave, donate_argnums=(1,))

    cache = init_cache(cfg, eng)
    toks, cache = fwd(params, cache, tokens)
    np.asarray(toks)  # compile + sync

    times = []
    for _ in range(args.reps):
        t0 = time.perf_counter()
        toks, cache = fwd(params, cache, tokens)
        np.asarray(toks)
        times.append(time.perf_counter() - t0)
    times.sort()

    # Matmul FLOPs only: the embedding table is a gather (0 FLOPs) and
    # the lm head runs over the S last rows, not all T.
    h, i = cfg.hidden_size, cfg.intermediate_size
    per_layer = h * (cfg.q_size + 2 * cfg.kv_size) + cfg.q_size * h + 3 * h * i
    flops = 2 * T * cfg.num_layers * per_layer + 2 * S * h * cfg.vocab_size
    peak = 197e12  # v5e bf16
    hbm = 819e9
    floor_flops = flops / peak * 1e3
    floor_bw = cfg.param_bytes() / hbm * 1e3
    print(
        f"# bucket={T} seqs={S} per={per}: "
        f"flops {flops/1e12:.2f} TF -> {floor_flops:.1f} ms MXU floor, "
        f"weights {floor_bw:.1f} ms HBM floor"
    )
    print(
        f"prefill wave: best {times[0]*1e3:.1f} ms, "
        f"median {times[len(times)//2]*1e3:.1f} ms "
        f"({T/times[0]:.0f} tok/s best)"
    )


if __name__ == "__main__":
    main()
