"""Speculative-decoding smoke: a mocker-backed frontend with
``--spec-decode ngram`` streams BIT-IDENTICAL greedy output with
speculation on vs off, and the worker reports acceptance rate > 0.

This is the user-visible contract of the spec subsystem (ISSUE 4):
speculation changes the step shape (several tokens per verify dispatch)
and the timing, never the tokens. The same request is sent twice — once
with the per-request ``dyn.spec_decode`` override disabling speculation,
once riding the engine default — and the full streamed text must match
byte for byte. The worker's /metrics must then show
``spec_decode_acceptance_rate`` > 0 and ``spec_draft``/``spec_verify``
spans in the trace collector.

CI usage (`.github/workflows/ci.yml` spec-smoke step) and local:

    python tools/spec_smoke.py
"""

from __future__ import annotations

import asyncio
import sys
from pathlib import Path

# Runnable straight from a checkout (CI also pip-installs the package).
sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


async def stream_text(session, url: str, body: dict) -> str:
    """POST a streaming chat completion; return the concatenated content."""
    import json

    parts: list[str] = []
    async with session.post(url, json=body) as resp:
        assert resp.status == 200, await resp.text()
        async for raw in resp.content:
            line = raw.decode("utf-8", "replace").strip()
            if not line.startswith("data:") or "[DONE]" in line:
                continue
            chunk = json.loads(line[len("data:"):])
            for choice in chunk.get("choices", []):
                parts.append((choice.get("delta") or {}).get("content") or "")
    return "".join(parts)


async def run() -> None:
    import aiohttp

    from dynamo_tpu import tracing
    from dynamo_tpu.backends.mocker import run_mocker
    from dynamo_tpu.frontend.main import run_frontend
    from dynamo_tpu.llm.mocker import MockEngineArgs
    from dynamo_tpu.runtime import DistributedRuntime
    from dynamo_tpu.runtime.status_server import SystemStatusServer
    from dynamo_tpu.runtime.store import StoreServer

    tracing.configure(enabled=True, sample=1.0)
    store = StoreServer()
    await store.start()
    worker_rt = await DistributedRuntime.create(store.address)
    # Status server so the spec gauges export exactly as deployed workers
    # export them (run_mocker binds them to runtime.status).
    worker_rt.status = SystemStatusServer(host="127.0.0.1", port=0)
    await worker_rt.status.start()
    served = asyncio.Event()
    worker = asyncio.create_task(
        run_mocker(
            worker_rt,
            model_name="mock",
            engine_args=MockEngineArgs(
                num_kv_blocks=8192,
                block_size=8,
                spec_decode="ngram",
                spec_k=4,
                spec_acceptance_rate=0.7,
                speedup_ratio=50.0,
            ),
            served_event=served,
        )
    )
    await asyncio.wait_for(served.wait(), 30)
    front_rt = await DistributedRuntime.create(store.address)
    ready = asyncio.Event()
    services: list = []
    frontend = asyncio.create_task(
        run_frontend(
            front_rt, http_host="127.0.0.1", http_port=0,
            router_mode="kv", ready_event=ready, service_out=services,
        )
    )
    await asyncio.wait_for(ready.wait(), 30)
    base = f"http://127.0.0.1:{services[0].port}"

    async with aiohttp.ClientSession() as s:
        for _ in range(200):
            async with s.get(f"{base}/v1/models") as r:
                if (await r.json())["data"]:
                    break
            await asyncio.sleep(0.05)
        else:
            raise TimeoutError("model never appeared on frontend")

        url = f"{base}/v1/chat/completions"

        def body(spec_override: dict | None) -> dict:
            out = {
                "model": "mock",
                "messages": [{"role": "user", "content": "speculate this"}],
                "max_tokens": 48,
                "temperature": 0.0,
                "stream": True,
            }
            if spec_override is not None:
                out["dyn"] = {"spec_decode": spec_override}
            return out

        text_off = await stream_text(s, url, body({"method": "off"}))
        text_on = await stream_text(s, url, body(None))  # engine default: on
        assert text_on and text_on == text_off, (
            f"speculative stream diverged from baseline:\n"
            f"  off: {text_off!r}\n  on:  {text_on!r}"
        )

        async with s.get(
            f"http://127.0.0.1:{worker_rt.status.port}/metrics"
        ) as r:
            metrics = await r.text()
        acc = next(
            (
                float(line.rsplit(" ", 1)[1])
                for line in metrics.splitlines()
                if line.startswith("dynamo_spec_decode_acceptance_rate{")
            ),
            None,
        )
        assert acc is not None, "spec_decode_acceptance_rate gauge missing"
        assert acc > 0, f"acceptance rate {acc} (speculation never accepted)"

        spans = {sp.name for sp in tracing.get_collector().stats()}
        assert "spec_draft" in spans and "spec_verify" in spans, spans

        print(
            "spec-smoke OK: 48-token greedy stream bit-identical spec-on "
            f"vs spec-off; acceptance_rate={acc:.3f}", flush=True,
        )

    for task in (worker, frontend):
        task.cancel()
    await worker_rt.status.stop()
    for rt in (worker_rt, front_rt):
        await rt.shutdown()
    await store.stop()


def main() -> int:
    asyncio.run(run())
    return 0


if __name__ == "__main__":
    sys.exit(main())
