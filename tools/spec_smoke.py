"""Speculative-decoding smoke: a mocker-backed frontend with
``--spec-decode ngram`` streams BIT-IDENTICAL greedy output with
speculation on vs off, and the worker reports acceptance rate > 0.

This is the user-visible contract of the spec subsystem (ISSUE 4):
speculation changes the step shape (several tokens per verify dispatch)
and the timing, never the tokens. The same request is sent twice — once
with the per-request ``dyn.spec_decode`` override disabling speculation,
once riding the engine default — and the full streamed text must match
byte for byte. The worker's /metrics must then show
``spec_decode_acceptance_rate`` > 0 and ``spec_draft``/``spec_verify``
spans in the trace collector.

Phase 3 (ISSUE 18) drives ON-DEVICE drafting through the same real
frontend: a ``--spec-device-draft`` worker under the universal megastep
vs a host-drafting twin at equal spec_k, same greedy request to each —
the streams must match byte for byte, and the device worker's /metrics
must show ``spec_device_rounds_total`` > 0 (at least one dispatch
actually ran multiple draft→verify→accept rounds inside the scan; a
drafter that silently degrades to host rounds passes parity but fails
this gauge).

CI usage (`.github/workflows/ci.yml` spec-smoke step) and local:

    python tools/spec_smoke.py
"""

from __future__ import annotations

import asyncio
import sys
from pathlib import Path

# Runnable straight from a checkout (CI also pip-installs the package).
sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


async def stream_text(session, url: str, body: dict) -> str:
    """POST a streaming chat completion; return the concatenated content."""
    import json

    parts: list[str] = []
    async with session.post(url, json=body) as resp:
        assert resp.status == 200, await resp.text()
        async for raw in resp.content:
            line = raw.decode("utf-8", "replace").strip()
            if not line.startswith("data:") or "[DONE]" in line:
                continue
            chunk = json.loads(line[len("data:"):])
            for choice in chunk.get("choices", []):
                parts.append((choice.get("delta") or {}).get("content") or "")
    return "".join(parts)


async def _stack(engine_args):
    """One full store + mocker-worker + frontend stack; returns the
    chat-completions URL, the worker's /metrics port, and a teardown."""
    import asyncio as aio

    import aiohttp

    from dynamo_tpu.backends.mocker import run_mocker
    from dynamo_tpu.frontend.main import run_frontend
    from dynamo_tpu.runtime import DistributedRuntime
    from dynamo_tpu.runtime.status_server import SystemStatusServer
    from dynamo_tpu.runtime.store import StoreServer

    store = StoreServer()
    await store.start()
    worker_rt = await DistributedRuntime.create(store.address)
    worker_rt.status = SystemStatusServer(host="127.0.0.1", port=0)
    await worker_rt.status.start()
    served = aio.Event()
    worker = aio.create_task(
        run_mocker(
            worker_rt, model_name="mock", engine_args=engine_args,
            served_event=served,
        )
    )
    await aio.wait_for(served.wait(), 30)
    front_rt = await DistributedRuntime.create(store.address)
    ready = aio.Event()
    services: list = []
    frontend = aio.create_task(
        run_frontend(
            front_rt, http_host="127.0.0.1", http_port=0,
            router_mode="kv", ready_event=ready, service_out=services,
        )
    )
    await aio.wait_for(ready.wait(), 30)
    base = f"http://127.0.0.1:{services[0].port}"
    async with aiohttp.ClientSession() as s:
        for _ in range(200):
            async with s.get(f"{base}/v1/models") as r:
                if (await r.json())["data"]:
                    break
            await aio.sleep(0.05)
        else:
            raise TimeoutError("model never appeared on frontend")

    async def teardown() -> None:
        for task in (worker, frontend):
            task.cancel()
        await worker_rt.status.stop()
        for rt in (worker_rt, front_rt):
            await rt.shutdown()
        await store.stop()

    return base, worker_rt.status.port, teardown


async def run_device_phase() -> None:
    """Phase 3: device-drafting worker vs host-drafting twin through the
    real frontend — byte-identical greedy streams, and the device worker
    proves >= 1 multi-round dispatch via spec_device_rounds_total."""
    import aiohttp

    from dynamo_tpu.llm.mocker import MockEngineArgs

    def args(device: bool) -> MockEngineArgs:
        return MockEngineArgs(
            num_kv_blocks=8192, block_size=8, spec_decode="ngram",
            spec_k=4, spec_acceptance_rate=0.7, speedup_ratio=50.0,
            megastep_k=4, spec_device_draft=device,
        )

    body = {
        "model": "mock",
        "messages": [{"role": "user", "content": "speculate this"}],
        "max_tokens": 48,
        "temperature": 0.0,
        "stream": True,
    }
    texts: dict[bool, str] = {}
    rounds = 0.0
    for device in (False, True):
        base, metrics_port, teardown = await _stack(args(device))
        async with aiohttp.ClientSession() as s:
            texts[device] = await stream_text(
                s, f"{base}/v1/chat/completions", dict(body)
            )
            async with s.get(
                f"http://127.0.0.1:{metrics_port}/metrics"
            ) as r:
                metrics = await r.text()
        if device:
            rounds = next(
                (
                    float(line.rsplit(" ", 1)[1])
                    for line in metrics.splitlines()
                    if line.startswith("dynamo_spec_device_rounds_total{")
                ),
                0.0,
            )
        await teardown()
    assert texts[True] and texts[True] == texts[False], (
        f"device-draft stream diverged from host-draft twin:\n"
        f"  host:   {texts[False]!r}\n  device: {texts[True]!r}"
    )
    assert rounds > 0, (
        "spec_device_rounds_total stayed 0 — no dispatch ran an on-device "
        "draft round (device drafting silently degraded to host rounds)"
    )
    print(
        "spec-smoke phase 3 OK: device-draft stream byte-identical to "
        f"host-draft twin; device_rounds={rounds:.0f}", flush=True,
    )


async def run() -> None:
    import aiohttp

    from dynamo_tpu import tracing
    from dynamo_tpu.backends.mocker import run_mocker
    from dynamo_tpu.frontend.main import run_frontend
    from dynamo_tpu.llm.mocker import MockEngineArgs
    from dynamo_tpu.runtime import DistributedRuntime
    from dynamo_tpu.runtime.status_server import SystemStatusServer
    from dynamo_tpu.runtime.store import StoreServer

    tracing.configure(enabled=True, sample=1.0)
    store = StoreServer()
    await store.start()
    worker_rt = await DistributedRuntime.create(store.address)
    # Status server so the spec gauges export exactly as deployed workers
    # export them (run_mocker binds them to runtime.status).
    worker_rt.status = SystemStatusServer(host="127.0.0.1", port=0)
    await worker_rt.status.start()
    served = asyncio.Event()
    worker = asyncio.create_task(
        run_mocker(
            worker_rt,
            model_name="mock",
            engine_args=MockEngineArgs(
                num_kv_blocks=8192,
                block_size=8,
                spec_decode="ngram",
                spec_k=4,
                spec_acceptance_rate=0.7,
                speedup_ratio=50.0,
            ),
            served_event=served,
        )
    )
    await asyncio.wait_for(served.wait(), 30)
    front_rt = await DistributedRuntime.create(store.address)
    ready = asyncio.Event()
    services: list = []
    frontend = asyncio.create_task(
        run_frontend(
            front_rt, http_host="127.0.0.1", http_port=0,
            router_mode="kv", ready_event=ready, service_out=services,
        )
    )
    await asyncio.wait_for(ready.wait(), 30)
    base = f"http://127.0.0.1:{services[0].port}"

    async with aiohttp.ClientSession() as s:
        for _ in range(200):
            async with s.get(f"{base}/v1/models") as r:
                if (await r.json())["data"]:
                    break
            await asyncio.sleep(0.05)
        else:
            raise TimeoutError("model never appeared on frontend")

        url = f"{base}/v1/chat/completions"

        def body(spec_override: dict | None) -> dict:
            out = {
                "model": "mock",
                "messages": [{"role": "user", "content": "speculate this"}],
                "max_tokens": 48,
                "temperature": 0.0,
                "stream": True,
            }
            if spec_override is not None:
                out["dyn"] = {"spec_decode": spec_override}
            return out

        text_off = await stream_text(s, url, body({"method": "off"}))
        text_on = await stream_text(s, url, body(None))  # engine default: on
        assert text_on and text_on == text_off, (
            f"speculative stream diverged from baseline:\n"
            f"  off: {text_off!r}\n  on:  {text_on!r}"
        )

        async with s.get(
            f"http://127.0.0.1:{worker_rt.status.port}/metrics"
        ) as r:
            metrics = await r.text()
        acc = next(
            (
                float(line.rsplit(" ", 1)[1])
                for line in metrics.splitlines()
                if line.startswith("dynamo_spec_decode_acceptance_rate{")
            ),
            None,
        )
        assert acc is not None, "spec_decode_acceptance_rate gauge missing"
        assert acc > 0, f"acceptance rate {acc} (speculation never accepted)"

        spans = {sp.name for sp in tracing.get_collector().stats()}
        assert "spec_draft" in spans and "spec_verify" in spans, spans

        print(
            "spec-smoke OK: 48-token greedy stream bit-identical spec-on "
            f"vs spec-off; acceptance_rate={acc:.3f}", flush=True,
        )

    for task in (worker, frontend):
        task.cancel()
    await worker_rt.status.stop()
    for rt in (worker_rt, front_rt):
        await rt.shutdown()
    await store.stop()


def main() -> int:
    asyncio.run(run())
    asyncio.run(run_device_phase())
    return 0


if __name__ == "__main__":
    sys.exit(main())
