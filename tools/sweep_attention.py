"""Sweep Pallas ragged-paged-attention grid constants at decode shapes.

The kernel's (num_kv_pages_per_block, num_queries_per_block) grid choice
dominates decode attention cost (tools/profile_decode.py measured
3.8 ms/step vs ~0.5 ms of KV traffic at bench shapes). Times a 64-long
scan of kernel calls per config so the per-invocation dispatch overhead
(~58 ms on the axon relay) amortizes away.

Usage: python tools/sweep_attention.py [--batch 32] [--ctx 192]
"""

from __future__ import annotations

import argparse
import time

import jax
import jax.numpy as jnp
import numpy as np

from dynamo_tpu.engine.config import EngineConfig, llama3_1b

def _time_chain(q, kv, kv_lens, tables, cu, num_seqs, sm_scale, kw, n_iters, n=3):
    from jax.experimental.pallas.ops.tpu.ragged_paged_attention import (
        ragged_paged_attention as kernel,
    )

    def chain(q, kv):
        def body(acc, _):
            out = kernel(
                q + acc * 0.0, kv, kv_lens, tables, cu, num_seqs,
                sm_scale=sm_scale, **kw,
            )
            return out, ()
        acc, _ = jax.lax.scan(body, q, jnp.arange(n_iters))
        return acc

    fn = jax.jit(chain)
    np.asarray(fn(q, kv))  # compile + sync
    best = float("inf")
    for _ in range(n):
        t0 = time.perf_counter()
        np.asarray(fn(q, kv))
        best = min(best, time.perf_counter() - t0)
    return best


def time_config(q, kv, kv_lens, tables, cu, num_seqs, sm_scale, kw):
    """Two chain lengths; the slope removes the fixed per-invocation
    dispatch/transfer overhead (~58 ms on the axon relay)."""
    args = (q, kv, kv_lens, tables, cu, num_seqs, sm_scale, kw)
    t16 = _time_chain(*args, 16)
    t64 = _time_chain(*args, 64)
    return (t64 - t16) / 48 * 1e3


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=32)
    ap.add_argument("--ctx", type=int, default=192)
    ap.add_argument("--blocks", type=int, default=512)
    ap.add_argument("--max-model-len", type=int, default=512)
    args = ap.parse_args()

    cfg = llama3_1b()
    engine = EngineConfig(
        num_kv_blocks=args.blocks, block_size=32, max_model_len=args.max_model_len
    )
    B = args.batch
    rng = np.random.RandomState(0)
    q = jnp.asarray(rng.randn(B, cfg.num_heads, cfg.head_dim), cfg.jax_dtype)
    kv = jnp.asarray(
        rng.randn(
            args.blocks + 1, engine.block_size, 2 * cfg.num_kv_heads, cfg.head_dim
        ),
        cfg.jax_dtype,
    )
    kv_lens = jnp.full((B,), args.ctx + 1, jnp.int32)
    per = engine.max_blocks_per_seq
    tables = jnp.asarray(
        rng.permutation(args.blocks)[: B * per].reshape(B, per)
        if args.blocks >= B * per
        else np.stack([rng.permutation(args.blocks)[:per] for _ in range(B)]),
        jnp.int32,
    )
    cu = jnp.arange(B + 1, dtype=jnp.int32)
    num_seqs = jnp.asarray([B], jnp.int32)
    sm_scale = cfg.head_dim ** -0.5

    kv_bytes = B * (args.ctx + 1) * 2 * cfg.num_kv_heads * cfg.head_dim * 2
    print(f"# B={B} ctx={args.ctx} pages/seq={per} one-layer kv read "
          f"{kv_bytes/1e6:.1f}MB -> roofline {kv_bytes/819e9*1e3:.4f} ms "
          f"(x{cfg.num_layers} layers)")

    configs = [("default", {})]
    for pages in (2, 4, 8, 16):
        if pages > per:
            continue
        for qb in (8, 16, 32, 64):
            if qb > max(B, 8):
                continue
            configs.append(
                (f"p{pages}_q{qb}",
                 dict(num_kv_pages_per_block=pages, num_queries_per_block=qb))
            )
    for name, kw in configs:
        try:
            t = time_config(q, kv, kv_lens, tables, cu, num_seqs, sm_scale, kw)
            print(f"{name:12s} {t:8.4f} ms/call  ({t*cfg.num_layers:7.3f} ms/model-step)")
        except Exception as e:  # noqa: BLE001
            print(f"{name:12s} FAILED: {type(e).__name__}: {str(e)[:100]}")


if __name__ == "__main__":
    main()
