"""Time the dense gather-based decode attention against the Pallas
kernel at bench decode shapes.

Hypothesis (from tools/profile_decode.py): at decode shapes the Pallas
ragged kernel is DMA-latency-bound at ~12x its KV traffic (~215 us/layer
at B=32 vs ~18 us of page reads). A dense XLA path — gather the whole
block table span into [T, span, heads, d], one masked softmax — moves
~2x the bytes (gather write+read) but is pure streaming, so it should
win whenever span (= max_model_len / block_size pages) is small.

Usage: python tools/time_dense_decode_attn.py [--batch 32] [--ctx 192]
"""

from __future__ import annotations

import argparse
import time
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from dynamo_tpu.engine.config import EngineConfig, llama3_1b
from dynamo_tpu.ops.ragged_attention import (
    ragged_paged_attention_ref,
)


def time_chain(fn, q, kv, n_iters, n=5):
    def chain(q, kv):
        def body(acc, _):
            return fn(acc, kv), ()

        acc, _ = jax.lax.scan(body, q, jnp.arange(n_iters))
        return acc

    jitted = jax.jit(chain)
    np.asarray(jitted(q, kv))
    best = float("inf")
    for _ in range(n):
        t0 = time.perf_counter()
        np.asarray(jitted(q, kv))
        best = min(best, time.perf_counter() - t0)
    return best


def slope(fn, q, kv):
    """Per-call cost from a 64->256 chain-length slope: 192 calls of
    signal dwarfs the relay's fixed-cost breathing (~±30 ms today),
    which wrecked shorter two-point fits (negative slopes)."""
    t64 = time_chain(fn, q, kv, 64)
    t256 = time_chain(fn, q, kv, 256)
    return (t256 - t64) / 192 * 1e3


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=32)
    ap.add_argument("--ctx", type=int, default=192)
    ap.add_argument("--blocks", type=int, default=512)
    ap.add_argument("--max-model-len", type=int, default=512)
    args = ap.parse_args()

    cfg = llama3_1b()
    engine = EngineConfig(
        num_kv_blocks=args.blocks, block_size=32, max_model_len=args.max_model_len
    )
    B = args.batch
    rng = np.random.RandomState(0)
    q = jnp.asarray(rng.randn(B, cfg.num_heads, cfg.head_dim), cfg.jax_dtype)
    kv = jnp.asarray(
        rng.randn(
            args.blocks + 1, engine.block_size, 2 * cfg.num_kv_heads, cfg.head_dim
        ),
        cfg.jax_dtype,
    )
    kv_lens = jnp.full((B,), args.ctx + 1, jnp.int32)
    per = engine.max_blocks_per_seq
    tables = jnp.asarray(
        np.stack([rng.permutation(args.blocks)[:per] for _ in range(B)]), jnp.int32
    )
    cu = jnp.arange(B + 1, dtype=jnp.int32)
    num_seqs = jnp.asarray([B], jnp.int32)
    sm_scale = cfg.head_dim ** -0.5

    span = per * engine.block_size
    gather_mb = B * span * 2 * cfg.num_kv_heads * cfg.head_dim * 2 / 1e6
    print(
        f"# B={B} ctx={args.ctx} span={span} gather={gather_mb:.1f}MB/layer "
        f"(x{cfg.num_layers} layers)"
    )

    def dense(qq, kv):
        return ragged_paged_attention_ref(
            qq, kv, kv_lens, tables, cu, num_seqs, sm_scale=sm_scale
        )

    def kernel(qq, kv):
        from jax.experimental.pallas.ops.tpu.ragged_paged_attention import (
            ragged_paged_attention as k,
        )

        return k(
            qq, kv, kv_lens, tables, cu, num_seqs, sm_scale=sm_scale,
            num_kv_pages_per_block=8, num_queries_per_block=8,
        )

    for name, fn in (("pallas_p8_q8", kernel), ("dense_gather", dense)):
        t = slope(fn, q, kv)
        print(f"{name:14s} {t:8.4f} ms/call ({t*cfg.num_layers:7.3f} ms/model-step)")


if __name__ == "__main__":
    main()
