"""Trace smoke: boot a mocker-backed frontend, send one request, serve /traces.

CI usage (`.github/workflows/ci.yml` trace-smoke step):

    python tools/trace_smoke.py --url-file /tmp/smoke_url --hold &
    # ... wait for the url file, then:
    curl -sf "$(cat /tmp/smoke_url)/traces" | python tools/trace_smoke.py --verify-stdin

Local one-shot (boots, requests, self-checks /traces, exits):

    python tools/trace_smoke.py

The verify step asserts the stitched-waterfall contract: one trace
containing at least {http, tokenize, route, prefill, decode} spans that
all share the root's trace id.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import sys
from pathlib import Path

REQUIRED_PHASES = ("http", "tokenize", "route", "prefill", "decode")


def verify_payload(payload: dict) -> str:
    """Assert the /traces contract; returns the stitched trace id."""
    assert payload.get("enabled"), "tracing reported disabled"
    for trace in payload.get("traces", []):
        spans = {sp["name"]: sp for sp in trace["spans"]}
        if all(p in spans for p in REQUIRED_PHASES):
            tids = {sp["trace_id"] for sp in trace["spans"]}
            assert tids == {trace["trace_id"]}, f"unstitched trace ids: {tids}"
            return trace["trace_id"]
    raise AssertionError(
        "no trace with phases "
        f"{REQUIRED_PHASES}: {[list({s['name'] for s in t['spans']}) for t in payload.get('traces', [])]}"
    )


async def run(url_file: str | None, hold: bool) -> None:
    import aiohttp

    from dynamo_tpu.backends.mocker import run_mocker
    from dynamo_tpu.frontend.main import run_frontend
    from dynamo_tpu.llm.mocker import MockEngineArgs
    from dynamo_tpu.runtime import DistributedRuntime
    from dynamo_tpu.runtime.store import StoreServer

    store = StoreServer()
    await store.start()
    worker_rt = await DistributedRuntime.create(store.address)
    served = asyncio.Event()
    worker = asyncio.create_task(
        run_mocker(
            worker_rt,
            model_name="mock",
            engine_args=MockEngineArgs(
                num_kv_blocks=2048, block_size=8, speedup_ratio=200.0
            ),
            served_event=served,
        )
    )
    await asyncio.wait_for(served.wait(), 30)
    front_rt = await DistributedRuntime.create(store.address)
    ready = asyncio.Event()
    services: list = []
    frontend = asyncio.create_task(
        run_frontend(
            front_rt, http_host="127.0.0.1", http_port=0,
            router_mode="kv", ready_event=ready, service_out=services,
        )
    )
    await asyncio.wait_for(ready.wait(), 30)
    base = f"http://127.0.0.1:{services[0].port}"

    async with aiohttp.ClientSession() as s:
        for _ in range(200):
            async with s.get(f"{base}/v1/models") as r:
                if (await r.json())["data"]:
                    break
            await asyncio.sleep(0.05)
        else:
            raise TimeoutError("model never appeared on frontend")
        body = {
            "model": "mock",
            "messages": [{"role": "user", "content": "trace smoke request"}],
            "max_tokens": 4,
            "stream": False,
        }
        async with s.post(f"{base}/v1/chat/completions", json=body) as r:
            assert r.status == 200, await r.text()

        if url_file:
            await asyncio.to_thread(Path(url_file).write_text, base)
        print(f"trace-smoke frontend up at {base}", flush=True)

        if hold:
            # Serve until killed (CI curls /traces from the shell).
            await asyncio.Event().wait()
        else:
            # One-shot self-check (engine spans land when streams close).
            payload = None
            for _ in range(40):
                async with s.get(f"{base}/traces?limit=20") as r:
                    assert r.status == 200
                    payload = await r.json()
                try:
                    tid = verify_payload(payload)
                    print(f"stitched trace OK: {tid}")
                    break
                except AssertionError:
                    await asyncio.sleep(0.05)
            else:
                verify_payload(payload)  # raise with the real diagnostic

    for rt in (worker_rt, front_rt):
        rt.signal_shutdown()
    for t in (worker, frontend):
        t.cancel()
    await store.stop()


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--url-file", help="write the frontend base url here once ready")
    ap.add_argument(
        "--hold", action="store_true",
        help="keep serving after the smoke request (CI curls from outside)",
    )
    ap.add_argument(
        "--verify-stdin", action="store_true",
        help="read a /traces JSON payload from stdin and assert the contract",
    )
    args = ap.parse_args(argv)
    if args.verify_stdin:
        tid = verify_payload(json.load(sys.stdin))
        print(f"stitched trace OK: {tid}")
        return 0
    asyncio.run(run(args.url_file, args.hold))
    return 0


if __name__ == "__main__":
    sys.exit(main())
